"""paddle_tpu.monitor.profile — per-operator cost attribution + roofline.

``monitor.xla`` records what a compiled executable costs as a whole
(flops, bytes, peak memory). This module answers the question ROADMAP
open item 3 actually asks — *which op, in which layer, is worth a
hand-written kernel?* — by parsing the optimized HLO text of a captured
executable, crediting every instruction's flops/bytes to the framework
scope that produced it, and ranking the resulting regions against the
device roofline.

Attribution rides on ``jax.named_scope``: XLA preserves the scope stack
of every traced eqn in instruction ``metadata={op_name=...}`` — through
fusion (inner instructions keep their own op_name), through the
backward pass (scopes resurface inside ``transpose(...)``/``jvp(...)``
wrappers), and through ``while``/``cond`` bodies. When profiling is
enabled (``profile.enable()`` or ``PADDLE_TPU_PROFILE=1`` next to the
monitor), every ``nn.Layer`` call, optimizer update body, and the fused
functional ops (softmax/xent/norm) run under a stable registered scope
name (``Linear_0``, ``opt.Adam``, ``F.softmax``, ...), so the ledger
rows name real model parts, not HLO serial numbers.

The flop/byte model mirrors XLA's ``HloCostAnalysis`` conventions
(dot = 2·out·K, elementwise = 1/elem, reduce = in−out with the
``to_apply`` region folded in, transcendentals counted separately,
shape ops free), verified against ``Compiled.cost_analysis()`` — the
reconciliation is asserted to 1% in tests/test_profile.py.

Cost discipline: when disabled (the default) the labeling sites check
one module flag (``profile.scopes_on``) and nothing else happens — no
scope objects, no HLO parse. ``report()`` is always explicit.

Usage::

    from paddle_tpu import monitor
    monitor.enable(); monitor.profile.enable()
    ... one jitted train step (aot-captured by monitor.xla) ...
    rep = monitor.profile.report()        # structured dict
    print(monitor.profile.format_table(rep))
"""
from __future__ import annotations

import os
import re
import threading
import time

__all__ = [
    "enable", "disable", "enabled", "scopes_on", "register_scope",
    "scopes", "layer_scope", "optimizer_scope", "fscope", "reset",
    "roofline_ceilings", "parse_hlo", "attribute", "report",
    "format_table", "last_report", "last_summary",
]

UNATTRIBUTED = "<unattributed>"

# scope kind taxonomy: "root" scopes (the to_static function name) exist
# so whole-step labels are recognized WITHOUT counting as attribution —
# everything lives under the root, so crediting it would make the ≥90%
# attribution bar trivially true.
_ATTRIBUTING_KINDS = ("layer", "optimizer", "functional", "op")

_lock = threading.Lock()
scopes_on = False           # read by nn.Layer/__call__, ops, optimizer
_scopes = {}                # scope name -> kind
_layer_counters = {}        # class name -> next per-instance index
_last = None                # cached last report() result


# ---------------------------------------------------------------------------
# lifecycle + scope registry

def enable():
    """Arm scope labeling (one module-flag check at each site when off)."""
    global scopes_on
    scopes_on = True


def disable():
    global scopes_on
    scopes_on = False


def enabled():
    return scopes_on


def register_scope(name, kind="layer"):
    """Register ``name`` as an attributable scope (kind: layer /
    optimizer / functional / op / root)."""
    with _lock:
        _scopes[str(name)] = kind
    return name


def scopes():
    with _lock:
        return dict(_scopes)


def layer_scope(layer):
    """Stable per-instance scope name for an nn.Layer: ``<Cls>_<k>`` in
    first-call order (deterministic for a fixed model + call order)."""
    name = layer.__dict__.get("_profile_scope")
    if name is None:
        cls = type(layer).__name__
        with _lock:
            k = _layer_counters.get(cls, 0)
            _layer_counters[cls] = k + 1
            name = f"{cls}_{k}"
            _scopes[name] = "layer"
        layer.__dict__["_profile_scope"] = name
    elif name not in _scopes:
        # a profile.reset() between runs cleared the registry but the
        # instance keeps its stable name — re-register, don't re-number
        with _lock:
            _scopes[name] = "layer"
    return name


def optimizer_scope(opt):
    """``opt.<Cls>`` — one scope per optimizer class instance."""
    name = getattr(opt, "_profile_scope", None)
    if name is None:
        name = f"opt.{type(opt).__name__}"
        try:
            opt._profile_scope = name
        except Exception:
            pass
    if name not in _scopes:
        with _lock:
            _scopes[name] = "optimizer"
    return name


def fscope(name):
    """Register-and-return a functional-op scope (``F.softmax`` ...)."""
    if name not in _scopes:
        with _lock:
            _scopes[name] = "functional"
    return name


def reset():
    """Clear registered scopes, per-class counters and the cached
    report (labeling flag is left as-is)."""
    global _last
    with _lock:
        _scopes.clear()
        _layer_counters.clear()
    _last = None


# ---------------------------------------------------------------------------
# roofline ceilings

# unknown silicon (the CPU test mesh) still needs a roofline to rank
# fusion candidates against — assume a v5e and say so in the report
ASSUMED_KIND = "TPU v5e"


def roofline_ceilings(device_kind=None):
    """Flops + HBM-bandwidth ceilings for ``device_kind`` (default: the
    local device, then $PADDLE_TPU_ROOFLINE_DEVICE, then an *assumed*
    v5e so CPU-side profiling still ranks). $PADDLE_TPU_FLOPS_CEILING
    (flops/s) and $PADDLE_TPU_HBM_GBPS override the tables."""
    from . import step as _step
    kind = device_kind or os.environ.get("PADDLE_TPU_ROOFLINE_DEVICE")
    if not kind:
        try:
            import jax
            kind = str(getattr(jax.local_devices()[0], "device_kind", ""))
        except Exception:
            kind = ""
    kind = str(kind)
    flops, bw = _step.ceilings_for_kind(kind)
    assumed = False
    if flops is None or bw is None:
        a_flops, a_bw = _step.ceilings_for_kind(ASSUMED_KIND)
        if flops is None:
            flops, assumed = a_flops, True
        if bw is None:
            bw, assumed = a_bw, True
        kind = f"{kind or 'unknown'} (assumed {ASSUMED_KIND})"
    env_f = os.environ.get("PADDLE_TPU_FLOPS_CEILING")
    if env_f:
        flops = float(env_f)
    env_b = os.environ.get("PADDLE_TPU_HBM_GBPS")
    if env_b:
        bw = float(env_b) * 1e9
    if env_f and env_b:
        assumed = False      # both ceilings pinned by the operator
    return {
        "device_kind": kind,
        "peak_flops": float(flops),
        "hbm_bytes_per_sec": float(bw),
        "ridge_flops_per_byte": float(flops) / float(bw),
        "assumed": assumed,
    }


# ---------------------------------------------------------------------------
# HLO text parsing (XLA HloCostAnalysis-compatible accounting)

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}
_TYPE_RE = re.compile(
    r"(pred|bf16|f16|f32|f64|f8\w+|s4|s8|s16|s32|s64|u4|u8|u16|u32|u64"
    r"|c64|c128)\[([0-9,]*)\]")
_INSTR_RE = re.compile(r"^\s*(ROOT\s+)?%?([\w.\-]+) = (.*)$")
_COMP_RE = re.compile(r"^\s*(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\{\s*$")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_REF_RE = {
    "to_apply": re.compile(r"to_apply=%?([\w.\-]+)"),
    "calls": re.compile(r"calls=%?([\w.\-]+)"),
    "inline": re.compile(r"(?:condition|body)=%?([\w.\-]+)"),
    "inline_set": re.compile(
        r"(?:branch_computations|called_computations)=\{([^}]*)\}"),
}
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_WINDOW_RE = re.compile(r"window=\{[^}]*\bsize=([0-9x]+)")
_FGC_RE = re.compile(r"feature_group_count=(\d+)")
_DIMLABEL_RE = re.compile(r"dim_labels=\w+_\w+->(\w+)")
_WRAPPER_RE = re.compile(
    r"^(jit|jvp|vjp|transpose|vmap|pmap|xmap|remat|checkpoint|"
    r"custom_jvp|custom_vjp|shard_map)\((.*)\)$")

# 1 flop per output element (HloCostAnalysis default for elementwise)
_ELEMENTWISE = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "compare", "select", "and", "or", "xor", "not", "negate", "abs",
    "sign", "floor", "ceil", "round-nearest-even", "round-nearest-afz",
    "power", "remainder", "clamp", "shift-left",
    "shift-right-arithmetic", "shift-right-logical", "is-finite",
    "popcnt", "count-leading-zeros", "stochastic-convert",
))
# counted in the separate `transcendentals` bucket, 0 flops
_TRANSCENDENTAL = frozenset((
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "logistic", "rsqrt", "sqrt", "cbrt", "tanh", "sine", "cosine",
    "tan", "atan2", "erf", "erf-inv", "expm1",
))
# pure bookkeeping: never a ledger row of its own
_SKIP_OPS = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id",
))


def _shape_elems(dims):
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n


def _type_bytes(s):
    """Total bytes of every array shape mentioned in a type string
    (a tuple type sums its components)."""
    total = 0
    for dt, dims in _TYPE_RE.findall(s):
        total += _shape_elems(dims) * _DTYPE_BYTES.get(dt, 4)
    return total


def _type_elems(s):
    """Total element count across array shapes in a type string."""
    total = 0
    for _dt, dims in _TYPE_RE.findall(s):
        total += _shape_elems(dims)
    return total


def _first_shape(s):
    m = _TYPE_RE.search(s)
    if not m:
        return []
    return [int(d) for d in m.group(2).split(",") if d]


def _balanced(s, i, open_ch="(", close_ch=")"):
    """Index one past the matching close bracket for s[i] == open_ch."""
    depth = 0
    for j in range(i, len(s)):
        if s[j] == open_ch:
            depth += 1
        elif s[j] == close_ch:
            depth -= 1
            if depth == 0:
                return j + 1
    return len(s)


def _split_top(s):
    """Split an operand list at top-level commas."""
    parts, depth, start = [], 0, 0
    for j, ch in enumerate(s):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(s[start:j].strip())
            start = j + 1
    tail = s[start:].strip()
    if tail:
        parts.append(tail)
    return parts


def _parse_instr(line):
    """One HLO instruction line -> dict, or None for non-instructions."""
    m = _INSTR_RE.match(line)
    if m is None:
        return None
    root, name, rest = bool(m.group(1)), m.group(2), m.group(3)
    # output type: tuple '(...)' or a single token up to the next space
    if rest.startswith("("):
        end = _balanced(rest, 0)
        out_type = rest[:end]
    else:
        end = rest.find(" ")
        if end < 0:
            return None
        out_type = rest[:end]
    rest = rest[end:].lstrip()
    om = re.match(r"([a-z][\w\-]*)\(", rest)
    if om is None:
        return None
    opcode = om.group(1)
    op_end = _balanced(rest, om.end() - 1)
    operands = rest[om.end():op_end - 1]
    attrs = rest[op_end:]
    nm = _OPNAME_RE.search(attrs)
    return {
        "name": name, "opcode": opcode, "out_type": out_type,
        "operands": _split_top(operands), "attrs": attrs,
        "op_name": nm.group(1) if nm else "", "root": root,
    }


def parse_hlo(text):
    """Parse optimized HLO text into ``{computation_name: {"entry": bool,
    "instrs": [...]}}`` plus reference sets. Returns (comps, entry_name,
    refs) where refs maps kind -> set of computation names referenced as
    to_apply (folded), calls (fused) or control-flow bodies (inline)."""
    comps, entry = {}, None
    cur = None
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped:
            continue
        if stripped == "}":
            cur = None
            continue
        if stripped.endswith("{") and ("->" in stripped
                                       or stripped.startswith("ENTRY")):
            cm = _COMP_RE.match(stripped)
            if cm:
                cur = cm.group(2)
                comps[cur] = {"entry": bool(cm.group(1)), "instrs": []}
                if cm.group(1):
                    entry = cur
                continue
        if cur is None:
            continue
        instr = _parse_instr(line)
        if instr is not None:
            comps[cur]["instrs"].append(instr)
    refs = {"to_apply": set(), "calls": set(), "inline": set()}
    for comp in comps.values():
        for instr in comp["instrs"]:
            attrs = instr["attrs"]
            for n in _REF_RE["to_apply"].findall(attrs):
                refs["to_apply"].add(n)
            for n in _REF_RE["calls"].findall(attrs):
                refs["calls"].add(n)
            for n in _REF_RE["inline"].findall(attrs):
                refs["inline"].add(n)
            for group in _REF_RE["inline_set"].findall(attrs):
                for tok in group.split(","):
                    tok = tok.strip().lstrip("%")
                    if tok:
                        refs["inline"].add(tok)
    return comps, entry, refs


def _instr_flops(instr, comps):
    """(flops, transcendentals) for one instruction, mirroring
    HloCostAnalysis conventions. Fusions sum their called computation."""
    opcode = instr["opcode"]
    if opcode == "fusion":
        f = t = 0
        for target in _REF_RE["calls"].findall(instr["attrs"]):
            comp = comps.get(target)
            if comp is None:
                continue
            for inner in comp["instrs"]:
                fi, ti = _instr_flops(inner, comps)
                f += fi
                t += ti
        return f, t
    out_elems = _type_elems(instr["out_type"])
    if opcode == "dot":
        contracted = 1
        cm = _CONTRACT_RE.search(instr["attrs"])
        if cm and instr["operands"]:
            lhs_dims = _first_shape(instr["operands"][0])
            for idx in cm.group(1).split(","):
                if idx and int(idx) < len(lhs_dims):
                    contracted *= lhs_dims[int(idx)]
        return 2 * out_elems * contracted, 0
    if opcode == "convolution":
        # 2 × out_elems × kernel_spatial × in_features/groups: the rhs
        # holds exactly (spatial × i × o) elements, so rhs_elems /
        # out_features is the per-output-element MAC count
        rhs_elems = (_type_elems(instr["operands"][1])
                     if len(instr["operands"]) > 1 else 0)
        out_features = 1
        dm = _DIMLABEL_RE.search(instr["attrs"])
        if dm:
            out_spec = dm.group(1)
            fpos = out_spec.find("f")
            out_dims = _first_shape(instr["out_type"])
            if 0 <= fpos < len(out_dims):
                out_features = max(1, out_dims[fpos])
        macs_per_out = rhs_elems // max(1, out_features)
        return 2 * out_elems * max(1, macs_per_out), 0
    if opcode == "reduce":
        ops = instr["operands"]
        arrays = ops[:max(1, len(ops) // 2)]
        in_elems = sum(_type_elems(o) for o in arrays)
        return max(0, in_elems - out_elems), 0
    if opcode == "reduce-window":
        wm = _WINDOW_RE.search(instr["attrs"])
        window = 1
        if wm:
            for d in wm.group(1).split("x"):
                if d:
                    window *= int(d)
        return out_elems * max(0, window - 1), 0
    if opcode in _TRANSCENDENTAL:
        return 0, out_elems
    if opcode in _ELEMENTWISE:
        return out_elems, 0
    return 0, 0


def _instr_bytes(instr):
    """Operand + output bytes (the HloCostAnalysis bytes_accessed
    convention: every operand read once, the output written once)."""
    b = _type_bytes(instr["out_type"])
    for op in instr["operands"]:
        b += _type_bytes(op)
    return b


def _scope_tokens(op_name):
    """named_scope path segments of an op_name, with jit()/jvp()/
    transpose()/... wrappers peeled recursively — backward-pass ops
    carry their forward scope inside transpose(jvp(scope))."""
    toks = []
    for raw in op_name.split("/"):
        t = raw.strip()
        while True:
            m = _WRAPPER_RE.match(t)
            if m is None:
                break
            t = m.group(2)
        if t:
            toks.append(t)
    return toks


def _region_of(op_name, scope_map):
    """(region_path, leaf_scope) from an op_name given the registry —
    the joined chain of registered attributable scopes, or
    (UNATTRIBUTED, None) when no registered scope appears."""
    hits = []
    for t in _scope_tokens(op_name):
        if scope_map.get(t) in _ATTRIBUTING_KINDS:
            if not hits or hits[-1] != t:
                hits.append(t)
    if not hits:
        return UNATTRIBUTED, None
    return "/".join(hits), hits[-1]


def attribute(text, scope_map=None):
    """Parse HLO ``text`` and attribute per-instruction cost to
    registered scopes. Returns a dict with ``ops`` rows (one per
    top-level instruction that does work), ``total_flops``,
    ``attributed_flops``, ``attributed_frac``, ``transcendentals``.

    Attribution is finest-granularity: a fusion's flops are credited
    per *inner* instruction op_name, so one fusion spanning two layers
    splits correctly; the row's own ``region`` is the dominant-flop
    region (falling back to the fusion's op_name when inner flops are
    all zero)."""
    scope_map = dict(_scopes) if scope_map is None else dict(scope_map)
    comps, entry, refs = parse_hlo(text)
    if entry is None:
        return {"ops": [], "total_flops": 0.0, "attributed_flops": 0.0,
                "attributed_frac": 0.0, "transcendentals": 0.0}

    # top-level stream: ENTRY + control-flow bodies (transitively),
    # skipping folded (to_apply) and fused (calls) computations
    top_names, work = [], [entry]
    seen = set(work)
    inline = refs["inline"] - refs["calls"] - refs["to_apply"]
    for name in sorted(inline):
        if name not in seen:
            seen.add(name)
            work.append(name)
    top_names = [n for n in work if n in comps]

    ops = []
    total_f = attr_f = total_t = 0.0
    for cname in top_names:
        for instr in comps[cname]["instrs"]:
            if instr["opcode"] in _SKIP_OPS:
                continue
            flops, trans = _instr_flops(instr, comps)
            nbytes = _instr_bytes(instr)
            if instr["opcode"] == "fusion":
                # split the fusion's flops across inner-instruction
                # scopes; dominant region becomes the row's region
                by_region = {}
                a = 0.0
                for target in _REF_RE["calls"].findall(instr["attrs"]):
                    comp = comps.get(target)
                    if comp is None:
                        continue
                    for inner in comp["instrs"]:
                        fi, _ti = _instr_flops(inner, comps)
                        reg, _leaf = _region_of(inner["op_name"],
                                                scope_map)
                        by_region[reg] = by_region.get(reg, 0.0) + fi
                        if reg != UNATTRIBUTED:
                            a += fi
                if by_region and any(v > 0 for v in by_region.values()):
                    region = max(by_region, key=by_region.get)
                else:
                    region, _ = _region_of(instr["op_name"], scope_map)
                    if region != UNATTRIBUTED:
                        a = flops
                leaf = region.rsplit("/", 1)[-1] \
                    if region != UNATTRIBUTED else None
                attributed = a
            else:
                region, leaf = _region_of(instr["op_name"], scope_map)
                attributed = flops if region != UNATTRIBUTED else 0.0
            if flops == 0 and trans == 0 and nbytes == 0:
                continue
            total_f += flops
            total_t += trans
            attr_f += attributed
            ops.append({
                "name": instr["name"], "opcode": instr["opcode"],
                "region": region, "scope": leaf,
                "scope_kind": scope_map.get(leaf),
                "flops": float(flops), "bytes": float(nbytes),
                "transcendentals": float(trans),
                "attributed_flops": float(attributed),
            })
    return {
        "ops": ops,
        "total_flops": float(total_f),
        "attributed_flops": float(attr_f),
        "attributed_frac": (attr_f / total_f) if total_f else 0.0,
        "transcendentals": float(total_t),
    }


# ---------------------------------------------------------------------------
# roofline classification + the ranked fusion menu

def _rooflined(ops, ceil):
    peak, bw = ceil["peak_flops"], ceil["hbm_bytes_per_sec"]
    for op in ops:
        t_c = op["flops"] / peak
        t_m = op["bytes"] / bw
        est = max(t_c, t_m)
        op["arith_intensity"] = (op["flops"] / op["bytes"]
                                 if op["bytes"] else None)
        op["est_time_s"] = est
        op["bound"] = "compute" if t_c >= t_m else "memory"
        op["mfu"] = (t_c / est) if est > 0 else None
        op["headroom_s"] = est - t_c
    return ops


def _regions(ops):
    regions = {}
    for op in ops:
        r = regions.setdefault(op["region"], {
            "region": op["region"], "scope_kind": op["scope_kind"],
            "ops": 0, "flops": 0.0, "bytes": 0.0,
            "transcendentals": 0.0, "est_time_s": 0.0,
            "compute_time_s": 0.0, "headroom_s": 0.0,
        })
        r["ops"] += 1
        r["flops"] += op["flops"]
        r["bytes"] += op["bytes"]
        r["transcendentals"] += op["transcendentals"]
        r["est_time_s"] += op["est_time_s"]
        r["compute_time_s"] += op["est_time_s"] - op["headroom_s"]
        r["headroom_s"] += op["headroom_s"]
    out = []
    for r in regions.values():
        r["bound"] = ("memory" if r["headroom_s"] > r["compute_time_s"]
                      else "compute")
        r["mfu"] = (r["compute_time_s"] / r["est_time_s"]
                    if r["est_time_s"] > 0 else None)
        out.append(r)
    # ranking: headroom first (time a perfect fusion could claw back),
    # flops and name as deterministic tie-breaks
    out.sort(key=lambda r: (-r["headroom_s"], -r["flops"], r["region"]))
    return out


def report(label=None, top_k=10, hlo=None, device_kind=None,
           emit_records=True):
    """Build the per-op cost ledger for a captured executable.

    ``label`` picks a ``monitor.xla`` capture (default: newest);
    ``hlo=`` profiles a raw HLO string instead. Returns a dict with
    per-op rows, per-region aggregation, ranked ``hotspots`` (top_k by
    fusion headroom), ceilings, and the reconciliation ratio against
    XLA's own ``cost_analysis()`` flop count — or None when nothing has
    been captured. Emits one JSONL ``hotspot`` record per hotspot and a
    ``profile.attributed_frac.<label>`` gauge when the monitor is on."""
    global _last
    from . import xla as _xla
    xla_flops = None
    if hlo is None:
        exe = _xla.executable(label)
        if exe is None:
            return None
        if label is None:
            newest = _xla.last()
            label = newest[0] if newest else None
        try:
            hlo = exe.as_text()
        except Exception:
            return None
        xla_flops = _xla.flops(label)
    ceil = roofline_ceilings(device_kind)
    attr = attribute(hlo)
    ops = _rooflined(attr["ops"], ceil)
    ops.sort(key=lambda o: (-o["est_time_s"], o["name"]))
    regions = _regions(ops)
    hotspots = []
    for rank, r in enumerate(regions[:max(0, int(top_k))], start=1):
        hotspots.append(dict(r, rank=rank))
    recon = (attr["total_flops"] / xla_flops
             if xla_flops else None)
    rep = {
        "kind": "profile_report",
        "ts": time.time(),
        "label": label,
        "ceilings": ceil,
        "total_flops": attr["total_flops"],
        "attributed_flops": attr["attributed_flops"],
        "attributed_frac": attr["attributed_frac"],
        "transcendentals": attr["transcendentals"],
        "xla_flops": xla_flops,
        "flops_reconciliation": recon,
        "ops": ops,
        "regions": regions,
        "hotspots": hotspots,
    }
    _last = rep
    from . import emit, enabled as _mon_enabled, gauge
    if emit_records and _mon_enabled():
        gauge(f"profile.attributed_frac.{label}").set(
            attr["attributed_frac"])
        for h in hotspots:
            emit(kind="hotspot", label=label, rank=h["rank"],
                 region=h["region"], scope_kind=h["scope_kind"],
                 ops=h["ops"], flops=h["flops"], bytes=h["bytes"],
                 est_time_s=h["est_time_s"],
                 headroom_s=h["headroom_s"], bound=h["bound"],
                 mfu=h["mfu"], device_kind=ceil["device_kind"],
                 assumed_roofline=ceil["assumed"])
    return rep


def last_report():
    """The most recent report() result (full ledger), or None."""
    return _last


def last_summary(top_k=5):
    """Compact view of the last report for /snapshot: label, attributed
    fraction, and the top-k hotspot regions."""
    rep = _last
    if rep is None:
        return None
    return {
        "label": rep["label"],
        "ts": rep["ts"],
        "device_kind": rep["ceilings"]["device_kind"],
        "assumed_roofline": rep["ceilings"]["assumed"],
        "attributed_frac": round(rep["attributed_frac"], 4),
        "total_flops": rep["total_flops"],
        "hotspots": [
            {"rank": h["rank"], "region": h["region"],
             "bound": h["bound"], "flops": h["flops"],
             "est_time_s": h["est_time_s"],
             "headroom_s": h["headroom_s"]}
            for h in rep["hotspots"][:top_k]
        ],
    }


def _fmt_num(v):
    if v is None:
        return "n/a"
    for unit, scale in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{unit}"
    return f"{v:.0f}"


def _fmt_time(v):
    if v is None:
        return "n/a"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.2f}us"


def format_table(rep, top_k=10):
    """Human-readable fusion menu for a report() dict."""
    if not rep:
        return "profile: no captured executable"
    c = rep["ceilings"]
    lines = [
        f"profile: {rep['label'] or '<hlo>'}  "
        f"[{c['device_kind']}  peak {_fmt_num(c['peak_flops'])}F/s  "
        f"hbm {_fmt_num(c['hbm_bytes_per_sec'])}B/s"
        f"{'  (assumed)' if c['assumed'] else ''}]",
        f"  flops {_fmt_num(rep['total_flops'])} "
        f"(attributed {rep['attributed_frac']:.1%}"
        + (f", xla recon {rep['flops_reconciliation']:.3f}"
           if rep.get("flops_reconciliation") else "") + ")",
        "",
        f"  {'#':>2} {'region':<40} {'bound':<7} {'flops':>9} "
        f"{'bytes':>9} {'AI':>7} {'est':>10} {'headroom':>10} {'mfu':>6}",
    ]
    for h in rep["hotspots"][:top_k]:
        ai = (h["flops"] / h["bytes"]) if h["bytes"] else None
        ai_s = f"{ai:.2f}" if ai is not None else "n/a"
        mfu_s = f"{h['mfu']:.1%}" if h["mfu"] is not None else "n/a"
        lines.append(
            f"  {h['rank']:>2} {h['region'][:40]:<40} {h['bound']:<7} "
            f"{_fmt_num(h['flops']):>9} {_fmt_num(h['bytes']):>9} "
            f"{ai_s:>7} {_fmt_time(h['est_time_s']):>10} "
            f"{_fmt_time(h['headroom_s']):>10} {mfu_s:>6}")
    return "\n".join(lines)
