"""paddle_tpu.monitor.sampler — the periodic device/host/SLO sampler.

Everything else in the monitor is *event-driven*: a counter ticks when
a step runs, a gauge moves when a request completes. But the questions
an operator asks a live run — "how close is HBM to the limit?", "is the
host leaking?", "what's the queue depth *right now*?", "did qps really
drop to zero or did the gauge just go stale?" — are about state, not
events, and state must be *sampled*. This daemon publishes, every
``interval_s`` (default 1s):

* ``mem.device.<id>.{bytes_in_use,peak_bytes_in_use,bytes_limit,
  hbm_headroom_bytes}`` and the cross-device totals
  ``mem.hbm_bytes_in_use`` / ``mem.hbm_peak_bytes_in_use`` /
  ``mem.hbm_headroom_bytes`` (limit − in-use, the number an operator
  actually watches), via ``step.device_memory_stats()``. A backend
  that exposes nothing (e.g. CPU) contributes NO ``mem.device.*``
  gauges at all — empty dicts stay out of the registry. When span
  tracing is live, each tick also drops one ``hbm.bytes_in_use``
  Chrome counter ("C") sample so Perfetto shows the measured
  occupancy under the span timeline.
* ``mem.host.rss_bytes`` — resident set size of this process
  (/proc/self/status VmRSS, falling back to getrusage peak)
* registered queue-depth providers — ``prefetch.queue_depth`` (each
  active ``prefetch_to_device``), ``serving.queue_depth`` (each live
  ``ServingEngine``), ``inference.executables`` (each Predictor's
  compiled-executable count)
* the serving tier's derived series — the decaying ``serving.qps``
  re-publish and the ``slo.{goodput,p50_ms,p99_ms}`` rollups — but
  only when ``paddle_tpu.serving`` is already imported; the sampler
  never drags the serving stack in

Cost discipline: nothing here runs unless :func:`monitor.serve` (or an
explicit :func:`start`) armed it — no thread, no provider calls, zero
hot-path presence. Providers register on the cold path (one dict write
per prefetch iterator / engine construction).

Provider contract: ``fn() -> {series_name: number}`` publishes gauges;
``fn() -> None`` (or raising) means the owner is gone and the provider
is dropped. Register/unregister with
:func:`register_provider` / :func:`unregister_provider`.
"""
from __future__ import annotations

import os
import threading

__all__ = [
    "Sampler", "start", "stop", "active", "sample_once",
    "register_provider", "unregister_provider",
]

DEFAULT_INTERVAL_S = 1.0

_providers_lock = threading.Lock()
_providers = {}           # key -> fn() -> {series: value} | None

_lock = threading.Lock()
_sampler = None           # the singleton started by monitor.serve()


# ---------------------------------------------------------------------------
# providers

def register_provider(key, fn):
    """Register a per-tick gauge source. Returns ``key`` (hand it to
    :func:`unregister_provider`); re-registering a key replaces it."""
    with _providers_lock:
        _providers[str(key)] = fn
    return str(key)


def unregister_provider(key):
    with _providers_lock:
        _providers.pop(str(key), None)


def _poll_providers(reg):
    with _providers_lock:
        items = list(_providers.items())
    dead = []
    for key, fn in items:
        try:
            series = fn()
        except Exception:
            series = None
        if series is None:
            dead.append(key)
            continue
        for name, value in series.items():
            if value is not None:
                reg.gauge(name).set(value)
    if dead:
        with _providers_lock:
            for key in dead:
                _providers.pop(key, None)


# ---------------------------------------------------------------------------
# the samples themselves

def _host_rss_bytes():
    """Linux VmRSS (current), else getrusage ru_maxrss (peak — still a
    usable leak watermark), else None."""
    try:
        with open("/proc/self/status", encoding="ascii",
                  errors="replace") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except Exception:
        pass
    try:
        import resource
        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports KiB, macOS bytes; normalize heuristically
        return int(rss_kb) * (1 if rss_kb > 1 << 30 else 1024)
    except Exception:
        return None


def sample_once(registry=None):
    """One sampler tick (also callable synchronously from tests): HBM
    watermarks, host RSS, registered queue depths, serving rollups.
    Publishes into the process registry regardless of ``enabled()`` —
    the scrape endpoint renders from the registry, and a pull-based
    surface must answer even when event instrumentation is off."""
    from .. import monitor as _mon
    from .step import device_memory_stats
    reg = registry if registry is not None else _mon.registry()

    mem = device_memory_stats()
    total_use = total_peak = total_headroom = 0
    have_hbm = have_headroom = False
    for did, stats in mem.items():
        if not stats:
            continue  # an all-empty dict (CPU) must not mint gauges
        for key, value in stats.items():
            reg.gauge(f"mem.device.{did}.{key}").set(value)
        if "bytes_in_use" in stats:
            have_hbm = True
            total_use += stats["bytes_in_use"]
            total_peak += stats.get("peak_bytes_in_use",
                                    stats["bytes_in_use"])
            if "bytes_limit" in stats:
                have_headroom = True
                headroom = stats["bytes_limit"] - stats["bytes_in_use"]
                total_headroom += headroom
                reg.gauge(
                    f"mem.device.{did}.hbm_headroom_bytes").set(headroom)
    if have_hbm:
        reg.gauge("mem.hbm_bytes_in_use").set(total_use)
        reg.gauge("mem.hbm_peak_bytes_in_use").set(total_peak)
        if have_headroom:
            reg.gauge("mem.hbm_headroom_bytes").set(total_headroom)
        # live HBM occupancy as a counter track under the span timeline
        from . import trace as _trace
        if _trace.enabled():
            _trace.counter("hbm.bytes_in_use", bytes=total_use)

    rss = _host_rss_bytes()
    if rss is not None:
        reg.gauge("mem.host.rss_bytes").set(rss)
        # host headroom vs PADDLE_TPU_HOST_MEM_LIMIT_BYTES (or the
        # autodetected MemTotal) — the budget the offload auto-picker
        # consults before paging optimizer state onto this host
        try:
            from ..memory_plan import host_mem_limit
            limit = host_mem_limit()
        except Exception:
            limit = None
        if limit is not None:
            reg.gauge("mem.host.headroom_bytes").set(limit - rss)

    _poll_providers(reg)

    # serving rollups only if the serving tier is actually loaded
    import sys
    smetrics = sys.modules.get("paddle_tpu.serving.metrics")
    if smetrics is not None:
        try:
            smetrics.publish_rollups()
        except Exception:
            pass
    # fleet health gauges (per-replica breaker state, active count) —
    # same lazy discipline
    smulti = sys.modules.get("paddle_tpu.serving.multi")
    if smulti is not None:
        try:
            smulti.publish_gauges()
        except Exception:
            pass


class Sampler:
    """Daemon thread calling :func:`sample_once` every ``interval_s``.
    ``stop()`` joins with a timeout so enable/disable cycles in tests
    can't leak threads."""

    def __init__(self, interval_s=DEFAULT_INTERVAL_S):
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="paddle_tpu-sampler", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None

    def running(self):
        return self._thread is not None and self._thread.is_alive()

    def _run(self):
        # first sample immediately: a scrape right after serve() should
        # already see mem.* gauges, not wait out an interval
        while True:
            try:
                sample_once()
            except Exception:
                pass  # a flaky backend must not kill the sampler
            if self._stop.wait(self.interval_s):
                return


# ---------------------------------------------------------------------------
# module-level singleton (owned by monitor.serve / monitor.disable)

def start(interval_s=None):
    """Start (or return) the process sampler singleton."""
    global _sampler
    if interval_s is None:
        env = os.environ.get("PADDLE_TPU_SAMPLER_INTERVAL_S", "")
        interval_s = float(env) if env else DEFAULT_INTERVAL_S
    with _lock:
        if _sampler is None:
            _sampler = Sampler(interval_s=interval_s).start()
        return _sampler


def stop(timeout=5.0):
    """Stop and join the singleton (idempotent)."""
    global _sampler
    with _lock:
        s, _sampler = _sampler, None
    if s is not None:
        s.stop(timeout=timeout)


def active():
    return _sampler
