"""paddle_tpu.monitor.trace — thread-aware span tracing + flight recorder.

The reference stack answered "where did the step's time go" with a
per-op CUDA timeline (reference: paddle/fluid/platform/profiler.cc,
device_tracer.cc, exported through chrome://tracing). This is the TPU
rebuild's equivalent: nested ``span("name")`` context managers record
begin/end events into a bounded ring buffer, one logical track per
thread, and :func:`export_chrome_trace` writes Chrome trace-event JSON
that Perfetto / chrome://tracing loads directly — the prefetch producer
thread, the host step loop and the watchdog each get their own track,
so pipeline overlap is *observed*, not inferred from counters.

Cost discipline (same contract as the dispatch hook): when tracing is
disabled — the default — ``span()`` does ONE module-flag check and
returns a shared null context manager; no event tuple, no clock read,
no dict. Enabling costs one ``perf_counter()`` + one deque append per
span edge (appends on ``collections.deque`` are atomic in CPython, so
producer threads never contend on a lock).

Usage::

    from paddle_tpu.monitor import trace

    trace.enable()                       # or PADDLE_TPU_TRACE=1
    with trace.span("epoch", epoch=0):
        ...
    trace.export_chrome_trace("/tmp/run.trace.json")   # open in Perfetto

Span sites wired by this package: ``Executor.run`` phases
(feed_prep/compile/execute/fetch), ``jit.<fn>`` compiled-step calls,
``prefetch.produce`` producer iterations, ``dataloader.assemble``,
``optimizer.step``, ``checkpoint.save``/``restore``,
``resilience.backoff`` waits, ``fit.step``; ``dispatch.<op>`` complete
events ride the existing ``time_dispatch`` opt-in, and collectives
appear as instant events. With ``bridge=True`` (or
``PADDLE_TPU_TRACE_BRIDGE=1``) each span additionally enters a
``jax.profiler.TraceAnnotation`` so the same names show up inside a
captured XLA device trace.

The flight recorder (:func:`flight_record`) turns "it hung at step
4017" into an artifact: on a watchdog stall, a NaN-guard rollback or an
unhandled crash in ``fit``/``Executor.run`` it dumps the last buffered
spans (as a loadable Chrome trace), the full counter snapshot, and the
HLO text of the most recently captured executable (monitor.xla) into a
timestamped directory.
"""
from __future__ import annotations

import collections
import functools
import json
import os
import re
import tempfile
import threading
import time

__all__ = [
    "enable", "disable", "enabled", "clear", "span", "complete",
    "instant", "counter", "traced", "events", "export_chrome_trace",
    "flight_record", "last_flight", "flow_start", "flow_step",
    "flow_end", "lane_complete", "lane_instant", "lanes",
]

DEFAULT_BUFFER = 65536

_CLOCK = time.perf_counter

_active = False
_bridge = False
_events = collections.deque(maxlen=DEFAULT_BUFFER)
_thread_names = {}          # thread ident -> name (first event wins)
_t0 = 0.0                   # perf_counter origin for export timestamps
_wall0 = 0.0                # wall clock at enable (for correlation)
_flight_lock = threading.Lock()
_flight_dumps = 0
_last_flight = None         # newest flight-recorder dir (/snapshot shows it)

# synthetic tracks ("lanes") that belong to a resource rather than a
# thread — KV slots, pools. Their tids sit in a range no pthread ident
# (a pointer-sized value) occupies, so each lane renders as its own
# named row in Perfetto.
_LANE_BASE = 1 << 20
_lanes = {}                 # lane name -> synthetic tid
_lane_lock = threading.Lock()


def last_flight():
    """Path of the most recent flight-recorder dump this process wrote,
    or None — the /snapshot health endpoint's pointer to post-mortem
    evidence."""
    return _last_flight


# ---------------------------------------------------------------------------
# lifecycle

def enabled():
    return _active


def enable(buffer_size=None, bridge=None):
    """Turn span recording on. ``buffer_size`` resizes the ring buffer
    (default 65536 events ≈ 32k spans — old events fall off the front);
    ``bridge=True`` additionally enters a jax.profiler.TraceAnnotation
    per span (``PADDLE_TPU_TRACE_BRIDGE=1``). Idempotent."""
    global _active, _bridge, _events, _t0, _wall0
    if buffer_size:
        _events = collections.deque(_events, maxlen=int(buffer_size))
    if bridge is None:
        bridge = os.environ.get(
            "PADDLE_TPU_TRACE_BRIDGE", "") not in ("", "0")
    _bridge = bool(bridge)
    if not _active:
        _t0 = _CLOCK()
        _wall0 = time.time()
        _active = True
    _note_thread(threading.get_ident())


def disable():
    """Stop recording. The buffer is KEPT so a post-run
    export_chrome_trace() still works; clear() empties it."""
    global _active
    _active = False


def clear():
    global _flight_dumps, _last_flight
    _events.clear()
    _thread_names.clear()
    with _lane_lock:
        _lanes.clear()
    _flight_dumps = 0
    _last_flight = None


def _note_thread(tid):
    if tid not in _thread_names:
        _thread_names[tid] = threading.current_thread().name


# ---------------------------------------------------------------------------
# recording

class _NullSpan:
    """The shared disabled-mode context manager: nothing allocated,
    nothing recorded."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


def _annotation(name):
    import jax.profiler
    return jax.profiler.TraceAnnotation(name)


class _Span:
    __slots__ = ("name", "args", "_ann")

    def __init__(self, name, args):
        self.name = name
        self.args = args
        self._ann = None

    def __enter__(self):
        tid = threading.get_ident()
        if tid not in _thread_names:
            _note_thread(tid)
        _events.append(("B", self.name, tid, _CLOCK(), self.args))
        if _bridge:
            try:
                self._ann = _annotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
            self._ann = None
        _events.append(("E", self.name, threading.get_ident(), _CLOCK()))
        return False


def span(name, **args):
    """``with trace.span("executor.execute", step=i): ...`` — records a
    begin/end event pair on the calling thread's track. Disabled mode
    returns the shared null context manager after one flag check."""
    if not _active:
        return _NULL
    return _Span(name, args or None)


def complete(name, t0, t1=None, **args):
    """Record an already-timed interval (the dispatch hook's path: t0
    was stamped by the time_dispatch machinery, so the span costs no
    extra clock read at the start)."""
    if not _active:
        return
    t1 = _CLOCK() if t1 is None else t1
    tid = threading.get_ident()
    if tid not in _thread_names:
        _note_thread(tid)
    _events.append(("X", name, tid, t0, t1 - t0, args or None))


def instant(name, **args):
    """A zero-duration marker (collective issue sites, fault firings)."""
    if not _active:
        return
    tid = threading.get_ident()
    if tid not in _thread_names:
        _note_thread(tid)
    _events.append(("I", name, tid, _CLOCK(), args or None))


def counter(name, values=None, ts=None, **kw):
    """A Chrome counter ("C") sample: ``values`` (dict) and/or keyword
    series render as a stacked counter track in Perfetto —
    ``trace.counter("hbm", bytes_in_use=x)``. ``ts=`` back/forward
    dates the sample on the perf_counter timeline (memory.report uses
    it to lay the predicted-occupancy curve out as one synthetic
    microsecond per schedule slot). Disabled mode is one flag check."""
    if not _active:
        return
    vals = dict(values) if values else {}
    if kw:
        vals.update(kw)
    if not vals:
        return
    tid = threading.get_ident()
    if tid not in _thread_names:
        _note_thread(tid)
    _events.append(("C", name, tid, _CLOCK() if ts is None else ts,
                    vals))


def _flow(kind, name, fid, args):
    if not _active:
        return
    tid = threading.get_ident()
    if tid not in _thread_names:
        _note_thread(tid)
    _events.append((kind, name, tid, _CLOCK(), int(fid), args or None))


def flow_start(name, fid, **args):
    """Open a flow (Perfetto arrow chain) with numeric id ``fid``. Flow
    events anchor to the innermost OPEN span on the calling thread, so
    emit them inside a ``span()`` — that is the slice the arrow leaves
    from."""
    _flow("FS", name, fid, args)


def flow_step(name, fid, **args):
    """Continue flow ``fid`` on the current thread (arrow lands on the
    enclosing slice, then leaves it again)."""
    _flow("FT", name, fid, args)


def flow_end(name, fid, **args):
    """Terminate flow ``fid`` at the enclosing slice."""
    _flow("FF", name, fid, args)


def _lane_tid(lane):
    with _lane_lock:
        tid = _lanes.get(lane)
        if tid is None:
            tid = _LANE_BASE + len(_lanes)
            _lanes[lane] = tid
            _thread_names[tid] = lane
        return tid


def lanes():
    """Registered lane names -> synthetic track ids."""
    with _lane_lock:
        return dict(_lanes)


def lane_complete(lane, name, t0, t1=None, **args):
    """Record a pre-timed interval on a named resource lane (a KV slot's
    occupied-by-request interval, a prefill admission) rather than on
    the calling thread's track. ``t0``/``t1`` are perf_counter stamps —
    the same clock ``span()`` uses, so lanes and thread tracks line up
    in one timeline."""
    if not _active:
        return
    t1 = _CLOCK() if t1 is None else t1
    _events.append(("X", name, _lane_tid(lane), t0, t1 - t0,
                    args or None))


def lane_instant(lane, name, ts=None, **args):
    """A zero-duration marker on a resource lane (pool growth pads)."""
    if not _active:
        return
    _events.append(("I", name, _lane_tid(lane),
                    _CLOCK() if ts is None else ts, args or None))


def traced(name=None):
    """Decorator form: ``@trace.traced`` or ``@trace.traced("label")``.
    Disabled mode adds one flag check per call."""
    def deco(fn):
        label = name if isinstance(name, str) else \
            getattr(fn, "__qualname__", getattr(fn, "__name__", "fn"))

        @functools.wraps(fn)
        def wrapped(*a, **k):
            if not _active:
                return fn(*a, **k)
            with _Span(label, None):
                return fn(*a, **k)
        return wrapped
    if callable(name):       # bare @traced
        return deco(name)
    return deco


def events(last=None):
    """Snapshot of the ring buffer (tuples; newest last). ``last=N``
    returns only the trailing N events."""
    evs = list(_events)
    return evs[-int(last):] if last else evs


# ---------------------------------------------------------------------------
# export

def _us(t):
    return round((t - _t0) * 1e6, 3)


def export_chrome_trace(path=None, last=None):
    """Render the buffer as Chrome trace-event JSON (the "JSON Array
    Format" with metadata): one ``pid`` per process, one ``tid`` track
    per thread (named via ``thread_name`` metadata events), ``B``/``E``
    pairs for spans, ``X`` complete events for pre-timed intervals
    (dispatch ops), ``i`` instants for markers. Load the file in
    https://ui.perfetto.dev or chrome://tracing.

    ``path=None`` returns the dict; a directory gets a
    ``trace-<pid>.json`` inside; any other path is written verbatim.
    Returns the written path (or the dict)."""
    pid = os.getpid()
    out = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": f"paddle_tpu[{pid}]"}}]
    for tid, tname in sorted(_thread_names.items()):
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": tname}})
    for ev in events(last=last):
        kind = ev[0]
        if kind == "B":
            _, name, tid, t, args = ev
            rec = {"ph": "B", "pid": pid, "tid": tid, "name": name,
                   "ts": _us(t), "cat": "span"}
        elif kind == "E":
            _, name, tid, t = ev
            rec = {"ph": "E", "pid": pid, "tid": tid, "name": name,
                   "ts": _us(t), "cat": "span"}
            args = None
        elif kind == "X":
            _, name, tid, t, dur, args = ev
            rec = {"ph": "X", "pid": pid, "tid": tid, "name": name,
                   "ts": _us(t), "dur": round(max(0.0, dur) * 1e6, 3),
                   "cat": "op"}
        elif kind == "C":
            _, name, tid, t, args = ev
            rec = {"ph": "C", "pid": pid, "tid": tid, "name": name,
                   "ts": _us(t), "cat": "counter"}
        elif kind in ("FS", "FT", "FF"):
            _, name, tid, t, fid, args = ev
            rec = {"ph": {"FS": "s", "FT": "t", "FF": "f"}[kind],
                   "pid": pid, "tid": tid, "name": name,
                   "ts": _us(t), "id": fid, "cat": "flow"}
            if kind == "FF":
                # bind to the enclosing slice even if no event starts
                # exactly at the arrow head
                rec["bp"] = "e"
        else:
            _, name, tid, t, args = ev
            rec = {"ph": "i", "pid": pid, "tid": tid, "name": name,
                   "ts": _us(t), "s": "t", "cat": "marker"}
        if args:
            rec["args"] = args
        out.append(rec)
    doc = {"traceEvents": out, "displayTimeUnit": "ms",
           "otherData": {"epoch_wall_s": _wall0, "pid": pid}}
    if path is None:
        return doc
    p = str(path)
    if not p.endswith(".json"):
        os.makedirs(p, exist_ok=True)
        p = os.path.join(p, f"trace-{pid}.json")
    else:
        parent = os.path.dirname(os.path.abspath(p))
        if parent:
            os.makedirs(parent, exist_ok=True)
    with open(p, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, default=str)
    return os.path.abspath(p)


# ---------------------------------------------------------------------------
# flight recorder

def flight_record(reason, step=None, directory=None, extra=None):
    """Dump post-mortem evidence to a timestamped directory and return
    its path (None when rate-capped or anything fails — the recorder
    must never add a second crash on top of the first).

    Layout::

        <base>/<stamp>-<reason>-<pid>-<n>/
            meta.json       reason / step / pid / sink path / extra
            counters.json   full registry snapshot
            trace.json      the span ring buffer as a Chrome trace
            hlo-<label>.txt HLO of the last captured executable (if any)
            op_ledger.json  monitor.profile per-op cost ledger (if any)
            memory_report.json  monitor.memory peak-contributor ledger

    ``base`` is ``directory=``, else $PADDLE_TPU_FLIGHT_DIR, else a
    ``flight/`` sibling of the monitor JSONL sink, else the system temp
    dir. At most $PADDLE_TPU_FLIGHT_MAX (default 8) dumps per process —
    a crash loop leaves evidence, not a full disk. Triggered by the
    resilience watchdog (stall), NaNGuard (rollback), and the crash
    handlers in ``hapi.Model.fit`` / ``Executor.run``."""
    global _flight_dumps
    try:
        from . import emit as _memit
        from . import jsonl_path as _mpath
        from . import snapshot as _msnap
        try:
            cap = int(os.environ.get("PADDLE_TPU_FLIGHT_MAX", "8") or 8)
        except ValueError:
            cap = 8
        with _flight_lock:
            if _flight_dumps >= cap:
                return None
            _flight_dumps += 1
            n = _flight_dumps
        base = directory or os.environ.get("PADDLE_TPU_FLIGHT_DIR")
        if not base:
            jp = _mpath()
            base = (os.path.join(os.path.dirname(jp), "flight") if jp
                    else os.path.join(tempfile.gettempdir(),
                                      "paddle_tpu_flight"))
        stamp = time.strftime("%Y%m%d-%H%M%S")
        safe_reason = re.sub(r"[^A-Za-z0-9_.-]+", "_", str(reason))
        d = os.path.join(base, f"{stamp}-{safe_reason}-{os.getpid()}-{n}")
        os.makedirs(d, exist_ok=True)

        meta = {"reason": str(reason), "step": step, "ts": time.time(),
                "pid": os.getpid(), "jsonl": _mpath(),
                "trace_enabled": _active, "events_buffered": len(_events)}
        if extra:
            meta["extra"] = {str(k): v for k, v in dict(extra).items()}
        with open(os.path.join(d, "meta.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(meta, fh, default=str, indent=1)
        with open(os.path.join(d, "counters.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(_msnap(), fh, default=str, indent=1)
        export_chrome_trace(os.path.join(d, "trace.json"))

        try:
            from . import xla as _xla
            hlo = _xla.hlo_text()
            if hlo:
                last = _xla.last()
                label = re.sub(r"[^A-Za-z0-9_.-]+", "_",
                               last[0] if last else "executable")
                with open(os.path.join(d, f"hlo-{label}.txt"), "w",
                          encoding="utf-8") as fh:
                    fh.write(hlo)
        except Exception:
            pass

        # the per-op cost ledger next to its HLO: the cached report if
        # one exists, else a fresh parse of the captured executable —
        # still inside the outer try, never a second crash
        try:
            from . import profile as _profile
            ledger = _profile.last_report()
            if ledger is None:
                ledger = _profile.report(emit_records=False)
            if ledger:
                with open(os.path.join(d, "op_ledger.json"), "w",
                          encoding="utf-8") as fh:
                    json.dump(ledger, fh, default=str, indent=1)
        except Exception:
            pass

        # the memory report + peak-contributor ledger next to the op
        # ledger (an OOM postmortem is exactly this pair): cached if
        # one exists, else a fresh simulation of the same executable
        try:
            from . import memory as _memory
            mrep = _memory.last_report()
            if mrep is None:
                mrep = _memory.report(emit_records=False)
            if mrep:
                with open(os.path.join(d, "memory_report.json"), "w",
                          encoding="utf-8") as fh:
                    json.dump(mrep, fh, default=str, indent=1)
        except Exception:
            pass

        # the slow-request exemplar ring next to the op/memory ledgers:
        # the N worst ttft/tpot waterfalls with full stage breakdowns —
        # "why was serving slow" evidence for a serving-side postmortem.
        # Lazy via sys.modules so telemetry never imports serving.
        try:
            import sys as _sys
            _rq = _sys.modules.get("paddle_tpu.serving.reqtrace")
            if _rq is not None:
                ex = _rq.exemplars()
                if ex.get("worst_ttft") or ex.get("worst_tpot"):
                    with open(os.path.join(d, "slow_requests.json"), "w",
                              encoding="utf-8") as fh:
                        json.dump(ex, fh, default=str, indent=1)
        except Exception:
            pass

        _memit(kind="flight_record", reason=str(reason), step=step,
               path=d)
        global _last_flight
        _last_flight = d
        return d
    except Exception:
        return None
