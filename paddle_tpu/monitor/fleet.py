"""paddle_tpu.monitor.fleet — the cross-process telemetry plane.

Every instrument below this module is per-process: one Registry, one
JSONL sink, one ``/metrics`` endpoint per PID. The pod-scale fleets the
serving tier replicates toward (ROADMAP item 3) need *fleet* answers —
"what is the fleet's p99 TTFT", "which replica is the straggler" — and
those are only computable from merged raw distributions, never from
averaging per-process percentiles. This module is the wire + merge
layer:

* **Snapshot publishing** — :class:`SnapshotPublisher` (armed by
  ``monitor.enable(telemetry_dir=...)`` or ``PADDLE_TPU_TELEMETRY_DIR``)
  periodically writes ``Registry.export_snapshot()`` — a versioned JSON
  body carrying counters, gauges, and *full-bounds* histogram exports —
  to ``<dir>/snap-<source>.json`` via tmp-file + ``os.replace``, so a
  reader never sees a torn snapshot. Disabled mode stays disabled: no
  thread, zero files.
* **Merging** — :class:`FleetAggregator` scrapes the directory and
  folds every fresh snapshot into fleet series: counters **sum**,
  gauges are **last-write-wins** by snapshot timestamp (and a source
  past ``staleness_ttl_s`` drops out of the rollup entirely — a dead
  replica must not pin its final gauges into the fleet view forever),
  histograms merge **bucket-wise** — legal exactly because every
  serving latency histogram shares :data:`~paddle_tpu.serving.metrics.
  LATENCY_BUCKETS_MS` bounds (asserted by tests/test_fleet.py, and by
  :func:`merge_histograms` itself at merge time). Fleet percentiles
  come from the merged bucket ladder: within one bucket width of the
  true union-of-events percentile.
* **Serving** — :func:`serve` starts an HTTP server whose ``/metrics``
  renders the *merged* registry as OpenMetrics and whose ``/fleet``
  returns the JSON rollup (per-source freshness, merged counters,
  fleet percentiles). A process-local exporter also answers ``/fleet``
  when this process hosts an aggregator (monitor/export.py routes it
  here).

Cost discipline: nothing in this module runs until a telemetry dir is
armed — no thread, no file I/O, no hot-path check anywhere. The
publisher's only steady-state cost is one ``export_snapshot()`` +
atomic file write per ``interval_s`` (its cumulative write time is
tracked in :func:`publisher_stats` — the telemetry smoke gate holds it
under 1% of wall time).
"""
from __future__ import annotations

import json
import os
import threading
import time

from .registry import Registry, SNAPSHOT_FORMAT_VERSION

__all__ = [
    "SNAPSHOT_PREFIX", "snapshot_path", "write_snapshot",
    "read_snapshots", "merge_histograms", "histogram_percentile",
    "FleetAggregator", "SnapshotPublisher", "start_publisher",
    "stop_publisher", "publisher_active", "publisher_stats",
    "serve", "stop_server", "active_aggregator",
    "DEFAULT_PUBLISH_INTERVAL_S", "DEFAULT_STALENESS_TTL_S",
]

SNAPSHOT_PREFIX = "snap-"
DEFAULT_PUBLISH_INTERVAL_S = 1.0
DEFAULT_STALENESS_TTL_S = 15.0


# ---------------------------------------------------------------------------
# snapshot files

def snapshot_path(telemetry_dir, source):
    """Where one process's snapshot lives. ``source`` must be filename
    safe; the default (``pid-<pid>``) is."""
    safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                   for c in str(source))
    return os.path.join(telemetry_dir, f"{SNAPSHOT_PREFIX}{safe}.json")


def write_snapshot(telemetry_dir, source=None, registry=None):
    """Atomically publish one snapshot: serialize to ``.tmp`` in the
    same directory, then ``os.replace`` over the final name — a
    concurrent scrape sees either the old complete snapshot or the new
    complete one, never a torn write. Returns the final path."""
    from .. import monitor as _mon
    reg = registry if registry is not None else _mon.registry()
    os.makedirs(telemetry_dir, exist_ok=True)
    snap = reg.export_snapshot(source=source)
    path = snapshot_path(telemetry_dir, snap["source"])
    tmp = f"{path}.tmp.{os.getpid()}"
    body = json.dumps(snap, default=str)
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(body)
    os.replace(tmp, path)
    return path


def read_snapshots(telemetry_dir):
    """Every parseable, format-compatible snapshot in the directory.
    Unparseable files (a writer killed pre-replace never leaves one,
    but a foreign file might) and other format generations are skipped,
    not raised — the aggregator must keep serving through one bad
    source."""
    out = []
    try:
        names = sorted(os.listdir(telemetry_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(SNAPSHOT_PREFIX)
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(telemetry_dir, name),
                      encoding="utf-8") as fh:
                snap = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        if snap.get("format_version") != SNAPSHOT_FORMAT_VERSION:
            continue
        out.append(snap)
    return out


# ---------------------------------------------------------------------------
# merge semantics

def merge_histograms(a, b):
    """Bucket-wise merge of two ``Histogram.export()`` dicts. Exact —
    the merged ladder is what one histogram observing the union of both
    event streams would hold — and only legal when the bounds agree,
    which is asserted, not assumed."""
    if list(a["bounds"]) != list(b["bounds"]):
        raise ValueError(
            "histogram merge with mismatched bucket bounds: "
            f"{len(a['bounds'])} vs {len(b['bounds'])} bounds "
            f"({a['bounds'][:3]}... vs {b['bounds'][:3]}...)")
    mins = [m for m in (a["min"], b["min"]) if m is not None]
    maxs = [m for m in (a["max"], b["max"]) if m is not None]
    return {"bounds": list(a["bounds"]),
            "counts": [x + y for x, y in zip(a["counts"], b["counts"])],
            "count": a["count"] + b["count"],
            "sum": a["sum"] + b["sum"],
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None}


def histogram_percentile(export, q):
    """Percentile estimate off a bucket ladder: the upper bound of the
    bucket where the cumulative count crosses ``q * count`` (overflow
    bucket reports the observed max). Always within one bucket width of
    the true population percentile — the resolution guarantee the
    telemetry smoke gate checks against its union-of-events oracle."""
    total = export["count"]
    if not total:
        return None
    # same nearest-rank convention as serving.metrics._percentile, so
    # the fleet estimate and the union-of-events oracle pick the same
    # sample's bucket
    target = min(total - 1, int(round(q * (total - 1)))) + 1
    cum = 0
    for i, c in enumerate(export["counts"]):
        cum += c
        if cum >= target:
            if i < len(export["bounds"]):
                return float(export["bounds"][i])
            return float(export["max"]) if export["max"] is not None \
                else None
    return float(export["max"]) if export["max"] is not None else None


def _merge_snapshots(snaps, now=None, staleness_ttl_s=None):
    """Fold snapshots into (merged dict, per-source meta). Stale
    sources (snapshot ``ts`` older than the TTL) are listed in the meta
    but contribute nothing to the merge."""
    now = time.time() if now is None else now
    counters, histograms = {}, {}
    gauges = {}           # name -> (ts, value)
    sources = []
    for snap in sorted(snaps, key=lambda s: s.get("ts", 0.0)):
        ts = float(snap.get("ts", 0.0))
        age = now - ts
        stale = (staleness_ttl_s is not None
                 and age > float(staleness_ttl_s))
        sources.append({"source": snap.get("source"),
                        "pid": snap.get("pid"),
                        "ts": ts, "age_s": round(age, 3),
                        "stale": stale})
        if stale:
            continue
        for name, v in snap.get("counters", {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, v in snap.get("gauges", {}).items():
            prev = gauges.get(name)
            if prev is None or ts >= prev[0]:
                gauges[name] = (ts, v)
        for name, h in snap.get("histograms", {}).items():
            histograms[name] = (merge_histograms(histograms[name], h)
                                if name in histograms else
                                {"bounds": list(h["bounds"]),
                                 "counts": list(h["counts"]),
                                 "count": h["count"], "sum": h["sum"],
                                 "min": h["min"], "max": h["max"]})
    merged = {"counters": counters,
              "gauges": {n: v for n, (_, v) in gauges.items()},
              "histograms": histograms}
    return merged, sources


class FleetAggregator:
    """Scrape-and-merge over a telemetry directory. ``scrape()``
    refreshes the merged view; ``registry()`` materializes it as a
    plain :class:`Registry` (what the merged ``/metrics`` endpoint
    renders); ``payload()`` is the ``/fleet`` JSON body."""

    def __init__(self, telemetry_dir,
                 staleness_ttl_s=DEFAULT_STALENESS_TTL_S):
        self.telemetry_dir = str(telemetry_dir)
        self.staleness_ttl_s = float(staleness_ttl_s)
        self._lock = threading.Lock()
        self._merged = {"counters": {}, "gauges": {}, "histograms": {}}
        self._sources = []
        self._snaps = []
        self._last_scrape = None
        self.scrapes = 0

    def scrape(self, now=None):
        """Read every snapshot and rebuild the merged view. Returns the
        merged dict. Cheap enough to call per poll tick — the cost is
        one ``json.load`` per live source."""
        now = time.time() if now is None else now
        snaps = read_snapshots(self.telemetry_dir)
        merged, sources = _merge_snapshots(
            snaps, now=now, staleness_ttl_s=self.staleness_ttl_s)
        fresh = [s for s in snaps
                 if now - float(s.get("ts", 0.0)) <= self.staleness_ttl_s]
        with self._lock:
            self._merged = merged
            self._sources = sources
            self._snaps = fresh
            self._last_scrape = time.time()
            self.scrapes += 1
        return merged

    def source_snapshots(self):
        """The raw fresh (non-stale) snapshots from the last scrape —
        the per-source view the anomaly detector diffs tick-over-tick
        (a merged rollup can say the fleet got slower; only per-source
        data can say *which replica*)."""
        with self._lock:
            return list(self._snaps)

    def merged(self):
        with self._lock:
            return self._merged

    def sources(self):
        """Per-source freshness meta from the last scrape (stale
        sources included, flagged)."""
        with self._lock:
            return list(self._sources)

    def value(self, name, default=0):
        """Merged scalar for one counter/gauge (counters win on a name
        collision, which the dotted naming scheme never produces)."""
        with self._lock:
            m = self._merged
            if name in m["counters"]:
                return m["counters"][name]
            return m["gauges"].get(name, default)

    def histogram(self, name):
        """The merged export dict for one histogram, or None."""
        with self._lock:
            return self._merged["histograms"].get(name)

    def percentile(self, name, q):
        h = self.histogram(name)
        return histogram_percentile(h, q) if h is not None else None

    def registry(self):
        """The merged view as a Registry (for OpenMetrics rendering).
        Rebuilt per call — the merge is the source of truth, not this
        materialization."""
        with self._lock:
            merged = self._merged
            reg = Registry()
            for name, v in merged["counters"].items():
                reg.counter(name).inc(v)
            for name, v in merged["gauges"].items():
                try:
                    reg.gauge(name).set(v)
                except (TypeError, ValueError):
                    continue
            for name, h in merged["histograms"].items():
                hist = reg.histogram(name, buckets=h["bounds"])
                hist._counts = list(h["counts"])
                hist.count = h["count"]
                hist.sum = h["sum"]
                hist.min = h["min"]
                hist.max = h["max"]
        return reg

    def payload(self):
        """The ``/fleet`` body: source freshness + merged series, with
        fleet p50/p99 precomputed for every merged histogram."""
        with self._lock:
            merged = self._merged
            sources = list(self._sources)
            last = self._last_scrape
        percentiles = {
            name: {"p50": histogram_percentile(h, 0.50),
                   "p99": histogram_percentile(h, 0.99),
                   "count": h["count"], "sum": h["sum"],
                   "min": h["min"], "max": h["max"]}
            for name, h in merged["histograms"].items()}
        return {"ts": time.time(), "last_scrape": last,
                "telemetry_dir": self.telemetry_dir,
                "staleness_ttl_s": self.staleness_ttl_s,
                "sources": sources,
                "live_sources": sum(1 for s in sources
                                    if not s["stale"]),
                "counters": merged["counters"],
                "gauges": merged["gauges"],
                "percentiles": percentiles}


# ---------------------------------------------------------------------------
# the publisher daemon (worker side)

class SnapshotPublisher:
    """Daemon thread writing this process's snapshot every
    ``interval_s``, plus once at ``stop()`` so a clean shutdown always
    leaves the final counter values on disk. Tracks its own cumulative
    write time — the overhead ledger the smoke gate reads."""

    def __init__(self, telemetry_dir, source=None,
                 interval_s=DEFAULT_PUBLISH_INTERVAL_S):
        self.telemetry_dir = str(telemetry_dir)
        self.source = source
        self.interval_s = float(interval_s)
        self.writes = 0
        self.write_s = 0.0       # wall span (includes GIL/sched waits)
        self.write_cpu_s = 0.0   # CPU actually burned publishing — the
        self._stop = threading.Event()   # overhead the smoke gate bills
        self._thread = None

    def publish_once(self):
        t0 = time.perf_counter()
        c0 = time.thread_time()
        path = write_snapshot(self.telemetry_dir, source=self.source)
        self.write_cpu_s += time.thread_time() - c0
        self.write_s += time.perf_counter() - t0
        self.writes += 1
        return path

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="paddle_tpu-fleet-publish",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout=5.0, final=True):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None
        if final:
            try:
                self.publish_once()
            except OSError:
                pass

    def running(self):
        return self._thread is not None and self._thread.is_alive()

    def _run(self):
        while True:
            try:
                self.publish_once()
            except OSError:
                pass  # a full disk must not kill the worker
            if self._stop.wait(self.interval_s):
                return


_lock = threading.Lock()
_publisher = None
_aggregator = None
_server = None


def start_publisher(telemetry_dir, source=None, interval_s=None):
    """Arm (or return) the process publisher singleton — called by
    ``monitor.enable(telemetry_dir=...)``. Re-arming with a different
    directory replaces the publisher."""
    global _publisher
    if interval_s is None:
        env = os.environ.get("PADDLE_TPU_TELEMETRY_INTERVAL_S", "")
        interval_s = float(env) if env else DEFAULT_PUBLISH_INTERVAL_S
    if source is None:
        source = os.environ.get("PADDLE_TPU_TELEMETRY_SOURCE") or None
    with _lock:
        pub = _publisher
        if (pub is not None
                and pub.telemetry_dir == str(telemetry_dir)
                and pub.running()):
            return pub
        if pub is not None:
            pub.stop(final=False)
        _publisher = SnapshotPublisher(
            telemetry_dir, source=source,
            interval_s=interval_s).start()
        return _publisher


def stop_publisher(timeout=5.0):
    """Stop + join the publisher (idempotent), writing one final
    snapshot so the aggregator sees the run's end state."""
    global _publisher
    with _lock:
        pub, _publisher = _publisher, None
    if pub is not None:
        pub.stop(timeout=timeout)


def publisher_active():
    pub = _publisher
    return pub is not None and pub.running()


def publisher_stats():
    """{"writes", "write_s", "interval_s"} for the live publisher, or
    None — the aggregation-overhead evidence the smoke gate banks."""
    pub = _publisher
    if pub is None:
        return None
    return {"writes": pub.writes, "write_s": round(pub.write_s, 6),
            "write_cpu_s": round(pub.write_cpu_s, 6),
            "interval_s": pub.interval_s}


# ---------------------------------------------------------------------------
# the aggregator HTTP plane

def active_aggregator():
    """The aggregator this process hosts (via :func:`serve`), or None —
    monitor/export.py routes its ``/fleet`` endpoint here."""
    return _aggregator


def serve(telemetry_dir, port=0, host="127.0.0.1",
          staleness_ttl_s=DEFAULT_STALENESS_TTL_S, scrape_interval_s=1.0):
    """Start the fleet aggregation server: a FleetAggregator scraping
    ``telemetry_dir`` every ``scrape_interval_s`` plus an HTTP server
    whose ``/metrics`` is the *merged* registry rendered as OpenMetrics
    and whose ``/fleet`` is the JSON rollup. Returns (aggregator,
    server). Idempotent per process."""
    global _aggregator, _server
    with _lock:
        if _server is not None:
            return _aggregator, _server
        agg = FleetAggregator(telemetry_dir,
                              staleness_ttl_s=staleness_ttl_s)
        agg.scrape()
        srv = _FleetServer(agg, port=port, host=host,
                           scrape_interval_s=scrape_interval_s)
        srv.start()
        _aggregator, _server = agg, srv
    from .. import monitor as _mon
    _mon.emit(kind="fleet", action="serve", dir=str(telemetry_dir),
              host=srv.host, port=srv.port)
    return agg, srv


def stop_server(timeout=5.0):
    """Tear down the fleet server + its scrape loop (idempotent)."""
    global _aggregator, _server
    with _lock:
        srv, _server = _server, None
        _aggregator = None
    if srv is not None:
        srv.stop(timeout=timeout)


class _FleetServer:
    """ThreadingHTTPServer on a daemon thread serving the merged view,
    with a sidecar scrape loop keeping the aggregator fresh."""

    def __init__(self, aggregator, port=0, host="127.0.0.1",
                 scrape_interval_s=1.0):
        import http.server
        from . import export as _export
        agg = aggregator

        class Handler(_export._Handler):
            def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._send(200, _export.render_openmetrics(
                            registry=agg.registry()),
                            _export.OPENMETRICS_CONTENT_TYPE)
                    elif path == "/fleet":
                        self._send(200, json.dumps(agg.payload(),
                                                   default=str),
                                   "application/json")
                    elif path == "/":
                        self._send(200, "paddle_tpu fleet telemetry: "
                                        "/metrics /fleet\n",
                                   "text/plain; charset=utf-8")
                    else:
                        self._send(404, "not found\n",
                                   "text/plain; charset=utf-8")
                except (BrokenPipeError, ConnectionResetError):
                    pass
                except Exception as e:  # noqa: BLE001 - scrape must not crash
                    try:
                        self._send(500, f"fleet telemetry error: {e!r}\n",
                                   "text/plain; charset=utf-8")
                    except Exception:
                        pass

        self.aggregator = aggregator
        self.scrape_interval_s = float(scrape_interval_s)
        self._httpd = http.server.ThreadingHTTPServer(
            (host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = int(self._httpd.server_address[1])
        self._thread = None
        self._scraper = None
        self._stop = threading.Event()

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="paddle_tpu-fleet", daemon=True)
            self._thread.start()
            self._scraper = threading.Thread(
                target=self._scrape_loop,
                name="paddle_tpu-fleet-scrape", daemon=True)
            self._scraper.start()
        return self

    def _scrape_loop(self):
        while not self._stop.wait(self.scrape_interval_s):
            try:
                self.aggregator.scrape()
            except Exception:
                pass  # one bad snapshot file must not kill the plane

    def stop(self, timeout=5.0):
        self._stop.set()
        try:
            self._httpd.shutdown()
        finally:
            self._httpd.server_close()
        for t in (self._thread, self._scraper):
            if t is not None:
                t.join(timeout=timeout)
        self._thread = self._scraper = None
