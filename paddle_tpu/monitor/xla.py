"""paddle_tpu.monitor.xla — XLA-measured cost of compiled executables.

The analytic MFU numbers (monitor.step's 6N flops/token, the ResNet
3×fwd constant) are *conventions*; XLA knows what it actually compiled.
A jax AOT ``Compiled`` object exposes ``cost_analysis()`` (flops, bytes
accessed) and ``memory_analysis()`` (argument/output/temp/alias bytes)
— this module pulls both into the monitor as per-executable gauges
(``xla.flops.<label>``, ``xla.bytes_accessed.<label>``,
``xla.peak_memory.<label>``) plus one ``xla_cost`` JSONL record, and
keeps the executables around so the flight recorder can dump HLO text.

``StepMonitor`` and bench.py report **measured MFU** (XLA-counted
flops ÷ step time ÷ peak) next to the analytic number, flagging >20%
divergence between the two flop counts — the cross-check the fusion
cost-model literature insists on (hand-rolled ceilings drift; the
compiler's own count doesn't).

Capture is free-riding, not double-compiling: :func:`aot_capture`
replaces a ``jax.jit`` callable with its AOT-compiled form
(``.lower(*args).compile()`` — the one compile the first call would
have paid anyway), records the analysis, and falls back to the
original callable on ANY failure, so instrumentation can never break a
step. ``Executor.run``/``warmup`` and ``jit.to_static`` call it on
their cache-miss paths when the monitor is enabled.
"""
from __future__ import annotations

import threading

__all__ = [
    "analyze", "capture", "aot_capture", "get", "flops",
    "bytes_accessed", "peak_memory", "labels", "last", "hlo_text",
    "executable", "measured_mfu", "reset",
]

MAX_ENTRIES = 64

_lock = threading.Lock()
_entries = {}       # label -> analysis dict
_execs = {}         # label -> the Compiled object (for HLO dumps)
_order = []         # labels, oldest first (insertion/refresh order)


def analyze(compiled):
    """Best-effort cost+memory extraction from an AOT Compiled object.
    Returns a (possibly empty) dict; never raises. Negative values
    (XLA's "unknown" marker on some backends) are dropped."""
    info = {}
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    if ca:
        d = ca[0] if isinstance(ca, (list, tuple)) else ca
        if isinstance(d, dict):
            for src, dst in (("flops", "flops"),
                             ("bytes accessed", "bytes_accessed"),
                             ("transcendentals", "transcendentals")):
                v = d.get(src)
                if v is not None and float(v) >= 0:
                    info[dst] = float(v)
    try:
        ms = compiled.memory_analysis()
    except Exception:
        ms = None
    if ms is not None:
        for attr, dst in (("argument_size_in_bytes", "argument_bytes"),
                          ("output_size_in_bytes", "output_bytes"),
                          ("temp_size_in_bytes", "temp_bytes"),
                          ("alias_size_in_bytes", "alias_bytes"),
                          ("generated_code_size_in_bytes", "code_bytes")):
            try:
                v = getattr(ms, attr, None)
            except Exception:
                v = None
            if v is not None and float(v) >= 0:
                info[dst] = float(v)
        peak = (info.get("argument_bytes", 0.0)
                + info.get("output_bytes", 0.0)
                + info.get("temp_bytes", 0.0)
                - info.get("alias_bytes", 0.0))
        if peak > 0:
            info["peak_memory"] = float(peak)
    return info


def capture(label, compiled):
    """Analyze + store under ``label`` (newest entry becomes
    :func:`last`), set the ``xla.*`` gauges and emit one ``xla_cost``
    JSONL record when the monitor is enabled. Returns the analysis dict
    (may be empty on exotic backends)."""
    label = str(label)
    info = analyze(compiled)
    with _lock:
        if label in _order:
            _order.remove(label)
        _order.append(label)
        _entries[label] = info
        _execs[label] = compiled
        while len(_order) > MAX_ENTRIES:
            old = _order.pop(0)
            _entries.pop(old, None)
            _execs.pop(old, None)
    from . import emit, enabled, gauge
    if enabled():
        for key, series in (("flops", "xla.flops"),
                            ("bytes_accessed", "xla.bytes_accessed"),
                            ("peak_memory", "xla.peak_memory")):
            if key in info:
                gauge(f"{series}.{label}").set(info[key])
        emit(kind="xla_cost", label=label, **info)
    return info


def aot_capture(fn, label, args):
    """AOT-compile ``fn`` at ``args`` (a tuple of the exact call
    arguments — lowering does NOT execute them), capture the analysis,
    and return the Compiled callable; an already-compiled object is
    captured in place. Any failure returns ``fn`` untouched — the
    caller keeps its working jitted entry."""
    try:
        if hasattr(fn, "cost_analysis"):       # already AOT-compiled
            capture(label, fn)
            return fn
        compiled = fn.lower(*args).compile()
        capture(label, compiled)
        return compiled
    except Exception:
        from . import counter, enabled
        if enabled():
            counter("xla.capture_failed").inc()
        return fn


def get(label=None):
    """The analysis dict for ``label`` (default: the most recently
    captured executable), or None."""
    with _lock:
        if label is None:
            if not _order:
                return None
            label = _order[-1]
        return _entries.get(str(label))


def flops(label=None):
    info = get(label)
    return info.get("flops") if info else None


def bytes_accessed(label=None):
    info = get(label)
    return info.get("bytes_accessed") if info else None


def peak_memory(label=None):
    info = get(label)
    return info.get("peak_memory") if info else None


def labels():
    with _lock:
        return list(_order)


def last():
    """(label, analysis) of the most recent capture, or None."""
    with _lock:
        if not _order:
            return None
        label = _order[-1]
        return label, _entries.get(label)


def executable(label=None):
    """The captured Compiled object for ``label`` (default: newest), or
    None — monitor.profile pulls untruncated HLO through this."""
    with _lock:
        if label is None:
            if not _order:
                return None
            label = _order[-1]
        return _execs.get(str(label))


def hlo_text(label=None, max_bytes=2_000_000):
    """HLO of a captured executable (default: newest), truncated to
    ``max_bytes``; None when unavailable. Truncation lands on a line
    boundary with an explicit ``... [truncated N bytes]`` tail so a
    flight-recorder dump stays parseable."""
    exe = executable(label)
    if exe is None:
        return None
    try:
        txt = exe.as_text()
    except Exception:
        return None
    if txt and max_bytes and len(txt) > max_bytes:
        cut = txt.rfind("\n", 0, max_bytes)
        if cut <= 0:
            cut = max_bytes
        dropped = len(txt) - cut
        txt = txt[:cut] + f"\n... [truncated {dropped} bytes]\n"
    return txt or None


def measured_mfu(step_time_s, label=None, peak_flops=None):
    """MFU from XLA-counted flops (vs. the analytic convention fed to
    StepMonitor). None when flops, peak or step time are unknown."""
    f = flops(label)
    if peak_flops is None:
        from .step import peak_flops_for_device
        peak_flops = peak_flops_for_device()
    if not f or not peak_flops or not step_time_s:
        return None
    return f / step_time_s / peak_flops


def reset():
    with _lock:
        _entries.clear()
        _execs.clear()
        _order.clear()
