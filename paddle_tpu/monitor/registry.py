"""paddle_tpu.monitor.registry — metric primitives + the JSONL event sink.

The reference stack's observability was per-op CUDA timing tables
(reference: paddle/fluid/platform/profiler.cc, device_tracer.cc) printed
at exit. This registry is the TPU rebuild's canonical store: counters,
gauges and histograms keyed by dotted names, all mutations behind one
lock, and a line-buffered JSONL sink so every run leaves a
machine-readable record a later tool (or the perf ledger) can ingest
without re-running anything.

Metric name convention (dotted, lowest-cardinality label last):

* ``dispatch.<op>``                 — per-op dispatch call counts
* ``dispatch.grad.<op>``            — the subset recorded on the tape
* ``dispatch.static.<op>``          — the subset recorded into a Program
* ``collective.<op>.<axis>.calls``  — collective issue counts per mesh axis
* ``collective.<op>.<axis>.bytes``  — per-shard payload bytes
* ``executor.{run,compile,cache_hit,cache_miss}``
* ``optimizer.step.<Class>``        — optimizer step entries
"""
from __future__ import annotations

import json
import os
import threading
import time


class Counter:
    """Monotonic counter. ``inc`` only; negative increments are a bug in
    the caller and raise."""

    kind = "counter"

    def __init__(self, name, lock):
        self.name = name
        self._lock = lock
        self._value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name}: negative inc {n}")
        with self._lock:
            self._value += n
        return self

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return self._value


class Gauge:
    """Last-write-wins scalar (step time, live memory, mfu...)."""

    kind = "gauge"

    def __init__(self, name, lock):
        self.name = name
        self._lock = lock
        self._value = None

    def set(self, v):
        with self._lock:
            self._value = float(v)
        return self

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return self._value


# default bounds cover ns-scale timings through multi-GB byte counts
_DEFAULT_BUCKETS = tuple(4.0 ** e for e in range(-10, 18))

#: version stamp on every cross-process snapshot (Registry.export_snapshot);
#: the fleet aggregator skips snapshots from a different format generation
#: instead of mis-merging them
SNAPSHOT_FORMAT_VERSION = 1


class Histogram:
    """Bucketed distribution: count/sum/min/max plus cumulative-style
    bucket counts (each observation lands in the first bound >= value;
    values past the last bound land in the +Inf overflow)."""

    kind = "histogram"

    def __init__(self, name, lock, buckets=None):
        self.name = name
        self._lock = lock
        self.buckets = tuple(sorted(buckets or _DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1
        return self

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0

    def openmetrics(self):
        """Cumulative-bucket view for the /metrics exporter: ordered
        ``[(upper_bound, cumulative_count), ...]`` (every bound, even
        empty ones — OpenMetrics `le` buckets must be monotonic and end
        at +Inf) plus sum/count, read atomically under the lock."""
        with self._lock:
            counts = list(self._counts)
            total, cum = 0, []
            for i, b in enumerate(self.buckets):
                total += counts[i]
                cum.append((b, total))
            return {"buckets": cum, "inf": total + counts[-1],
                    "sum": self.sum, "count": self.count}

    def snapshot(self):
        out = {"count": self.count, "sum": self.sum, "min": self.min,
               "max": self.max}
        # only the populated buckets — full default bounds are noise
        out["buckets"] = {
            ("inf" if i == len(self.buckets) else repr(self.buckets[i])): c
            for i, c in enumerate(self._counts) if c}
        return out

    def export(self):
        """Mergeable full-fidelity view for the fleet telemetry plane:
        EVERY bound (not just populated ones — two exports merge
        bucket-wise only when their bounds align) plus per-bucket raw
        (non-cumulative) counts, read atomically under the lock. The
        inverse/merge helpers live in monitor/fleet.py."""
        with self._lock:
            return {"bounds": list(self.buckets),
                    "counts": list(self._counts),
                    "count": self.count, "sum": self.sum,
                    "min": self.min, "max": self.max}


class Registry:
    """Name → metric store. One RLock guards creation and every
    mutation; get-or-create with a conflicting type raises."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics = {}

    def _get_or_create(self, name, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, self._lock, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name, buckets=None) -> Histogram:
        return self._get_or_create(name, Histogram, buckets=buckets)

    def get(self, name):
        return self._metrics.get(name)

    def remove(self, name):
        """Drop one metric by exact name (stale-gauge hygiene: a closed
        replica's per-replica gauges must not linger in rollups forever).
        Returns True when something was removed."""
        with self._lock:
            return self._metrics.pop(name, None) is not None

    def clear_prefix(self, prefix):
        """Drop every metric under a dotted prefix (a replica's whole
        per-source series family in one call). Returns how many went."""
        if not prefix:
            return 0
        with self._lock:
            doomed = [n for n in self._metrics if n.startswith(prefix)]
            for n in doomed:
                del self._metrics[n]
        return len(doomed)

    def value(self, name, default=0):
        """Current scalar for a counter/gauge; a histogram (which has no
        single value) returns its snapshot dict. Missing -> default."""
        m = self._metrics.get(name)
        if m is None:
            return default
        if isinstance(m, Histogram):
            return m.snapshot()
        return m.value

    def names(self, prefix=""):
        with self._lock:
            return sorted(n for n in self._metrics if n.startswith(prefix))

    def snapshot(self, prefix=""):
        """{name: scalar-or-dict} for every metric under `prefix`."""
        with self._lock:
            return {n: m.snapshot() for n, m in sorted(self._metrics.items())
                    if n.startswith(prefix)}

    def export_snapshot(self, source=None, prefix=""):
        """The versioned cross-process snapshot body the fleet
        aggregation plane ships between processes: counters and gauges
        as scalars, histograms as full-bounds :meth:`Histogram.export`
        dicts (mergeable). ``source`` labels the producing process;
        the aggregator trusts ``ts`` for gauge last-write-wins and
        staleness aging. See monitor/fleet.py for the file protocol."""
        with self._lock:
            items = sorted((n, m) for n, m in self._metrics.items()
                           if n.startswith(prefix))
        counters, gauges, histograms = {}, {}, {}
        for name, m in items:
            if isinstance(m, Histogram):
                histograms[name] = m.export()
            elif isinstance(m, Counter):
                counters[name] = m.value
            elif m.value is not None:
                gauges[name] = m.value
        return {"format_version": SNAPSHOT_FORMAT_VERSION,
                "source": str(source) if source is not None
                else f"pid-{os.getpid()}",
                "pid": os.getpid(), "ts": time.time(),
                "counters": counters, "gauges": gauges,
                "histograms": histograms}

    def collect(self):
        """Exporter feed: ``[(name, kind, payload), ...]`` sorted by
        name — scalar value for counters/gauges, the ``openmetrics()``
        dict for histograms. The metric list is snapshotted under the
        lock; per-metric reads then re-take it, so a scrape racing N
        writer threads always sees each metric at some consistent
        point (counters monotonic scrape-over-scrape)."""
        with self._lock:
            items = sorted(self._metrics.items())
        out = []
        for name, m in items:
            if isinstance(m, Histogram):
                out.append((name, m.kind, m.openmetrics()))
            else:
                out.append((name, m.kind, m.value))
        return out

    def reset(self):
        with self._lock:
            self._metrics.clear()


#: how many rotated generations a size-capped JsonlSink keeps
#: (events.jsonl -> events.jsonl.1 -> events.jsonl.2 -> dropped)
SINK_ROTATIONS = 2


class JsonlSink:
    """Append-only JSONL event writer. Every record gets a wall-clock
    ``ts``; writes are line-atomic under a lock and flushed eagerly so a
    killed run keeps everything emitted before the kill.

    ``max_bytes`` caps the live file: once an emit pushes it past the
    cap the file rotates (``path`` -> ``path.1`` -> ``path.2``, oldest
    dropped) and ``path`` reopens fresh. ``self.path`` never changes
    across a rotation — the flight recorder and ``jsonl_path()`` keep
    pointing at the live file, so a soak-length chaos run rotates
    underneath them instead of growing without bound."""

    def __init__(self, path, max_bytes=None):
        self.path = os.path.abspath(path)
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.rotations = 0
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", encoding="utf-8")
        self._size = self._fh.tell()

    def _rotate_locked(self):
        self._fh.close()
        for gen in range(SINK_ROTATIONS, 1, -1):
            older = f"{self.path}.{gen - 1}"
            if os.path.exists(older):
                os.replace(older, f"{self.path}.{gen}")
        os.replace(self.path, f"{self.path}.1")
        self._fh = open(self.path, "a", encoding="utf-8")
        self._size = 0
        self.rotations += 1

    def emit(self, record: dict):
        record.setdefault("ts", time.time())
        line = json.dumps(record, default=str)
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            if self.max_bytes is not None:
                self._size += len(line) + 1
                if self._size > self.max_bytes:
                    self._rotate_locked()

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_jsonl(path):
    """Parse a sink file back into a list of dicts (the test/tooling
    round-trip helper). A run killed mid-write leaves a truncated final
    line — any unparseable line is skipped with a warning instead of
    raising, so post-mortem tooling can always read what DID land."""
    import warnings
    out = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                warnings.warn(
                    f"read_jsonl: skipping unparseable line {lineno} of "
                    f"{path} (truncated write from a killed run?)")
    return out
