"""paddle_tpu.monitor.alerts — SLO burn-rate alerting + anomaly detection.

The fleet plane (monitor/fleet.py) answers "what is the fleet's p99";
this module answers "should a human (or the supervisor) care". Two
mechanisms, both first-class event streams:

**Burn-rate rules** (:class:`BurnRateRule` + :class:`AlertManager`) —
the multi-window pattern from SRE practice: an SLO with target ``t``
(say 99% of TTFT samples under 500 ms) has an error budget of
``1 - t``; the *burn rate* over a window is the observed breach
fraction divided by that budget. A rule fires only when BOTH a fast
window (default 60 s — "it is happening right now") and a slow window
(default 1800 s — "it has been happening long enough to matter") burn
above the threshold; it resolves when the fast window is clean again.
That combination pages quickly on hard outages and stays quiet through
one-sample blips — a single bad scrape can never page. States walk
``pending`` (fast breaching, slow not yet) → ``firing`` → ``resolved``,
each transition emitted as a ``kind="alert"`` JSONL event and mirrored
in ``alerts.firing`` / ``alerts.fired`` metrics.

**Anomaly findings** (:class:`AnomalyDetector`) — the failure shapes
the chaos suites already induce, detected from per-source snapshot
deltas, each finding naming the offending source/series:

* *compile storm* — post-warmup growth of the compile counters
  (``executor.compile``/``executor.recompile``/``jit.compile``/
  ``jit.recompile``/``serving.decode.compiles``): a steady-state
  server minting executables is re-tracing every batch.
* *straggler* — one source's mean decode-step time z-scored against
  the *other* sources (leave-one-out, with a floored sigma — with a
  four-replica fleet a plain fleet-wide z-score mathematically cannot
  exceed 1.5, so it would never fire).
* *accept-rate collapse* — ``serving.decode.accept_rate`` falling
  under a floor after having been healthy (a speculative draft gone
  cold mid-run, not one that never warmed).
* *queue-depth divergence* — one source's queue depth a multiple of
  the fleet median: traffic is routing to a replica that can't drain.

Findings promote straight to ``firing`` through
:meth:`AlertManager.raise_finding` (anomalies are edge-detected, not
budget-burned) and resolve once the detector stops reporting them.
The currently-active findings are published module-globally
(:func:`active_findings`) so ``ServingSupervisor`` can cite the
anomaly behind a drain/scale decision — see serving/supervisor.py.

Nothing here polls on its own: an AlertManager/AnomalyDetector ticks
only when its owner (the telemetry smoke's aggregator loop, a test, an
operator script) calls it. Zero cost when unused.
"""
from __future__ import annotations

import threading
import time

__all__ = [
    "BurnRateRule", "Alert", "AlertManager", "AnomalyDetector",
    "active_findings", "set_active_findings", "clear_findings",
    "DEFAULT_RULES", "default_rules",
]

#: compile counters whose post-warmup growth constitutes a storm
COMPILE_SERIES = ("executor.compile", "executor.recompile",
                  "jit.compile", "jit.recompile",
                  "serving.decode.compiles")


# ---------------------------------------------------------------------------
# burn-rate rules

class BurnRateRule:
    """One SLO burn-rate rule over a scalar series.

    ``direction="above"`` means a sample breaches when it exceeds
    ``objective`` (latency-style); ``"below"`` when it falls under
    (throughput/goodput-style). ``budget`` is the allowed breach
    fraction (0.01 = a 99% SLO); ``burn_threshold`` is how many times
    budget both windows must burn before the rule fires."""

    def __init__(self, name, series, objective, direction="above",
                 budget=0.01, burn_threshold=2.0,
                 fast_window_s=60.0, slow_window_s=1800.0):
        if direction not in ("above", "below"):
            raise ValueError(f"direction {direction!r}")
        self.name = str(name)
        self.series = str(series)
        self.objective = float(objective)
        self.direction = direction
        self.budget = float(budget)
        self.burn_threshold = float(burn_threshold)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)

    def breaches(self, value):
        v = float(value)
        return v > self.objective if self.direction == "above" \
            else v < self.objective


def default_rules(ttft_p99_objective_ms=500.0, tokens_floor=1.0,
                  goodput_target=0.9, **kw):
    """The stock rule catalogue over the serving SLO surface (see
    docs/observability.md for the burn-rate math)."""
    return [
        BurnRateRule("slo-ttft-p99", "slo.ttft_p99_ms",
                     ttft_p99_objective_ms, direction="above", **kw),
        BurnRateRule("slo-tokens-per-s", "slo.tokens_per_s",
                     tokens_floor, direction="below", **kw),
        BurnRateRule("slo-goodput", "slo.goodput",
                     goodput_target, direction="below", **kw),
    ]


DEFAULT_RULES = default_rules


class Alert:
    """Lifecycle record for one rule/finding: pending → firing →
    resolved, with timestamps for each edge (the detection-latency
    evidence bench.py banks)."""

    def __init__(self, name, series=None, source=None, context=None):
        self.name = name
        self.series = series
        self.source = source
        self.context = dict(context or {})
        self.state = "pending"
        self.pending_at = None
        self.fired_at = None
        self.resolved_at = None

    def as_dict(self):
        return {"name": self.name, "series": self.series,
                "source": self.source, "state": self.state,
                "pending_at": self.pending_at,
                "fired_at": self.fired_at,
                "resolved_at": self.resolved_at,
                "context": dict(self.context)}


class AlertManager:
    """Evaluates burn-rate rules against a value source and hosts
    finding-driven alerts. ``source`` is ``fn(series) -> value|None``
    — defaulting to the process registry, or wire it to a
    ``FleetAggregator.value`` for fleet-level alerting. Call
    :meth:`tick` once per evaluation interval."""

    def __init__(self, rules=None, source=None,
                 finding_resolve_after_s=5.0):
        self.rules = list(rules if rules is not None else [])
        self._source = source
        self.finding_resolve_after_s = float(finding_resolve_after_s)
        self._lock = threading.Lock()
        self._samples = {}      # rule.name -> deque[(t, breached)]
        self._alerts = {}       # alert key -> Alert
        self._finding_seen = {}  # alert key -> last raise_finding ts
        self.history = []       # every state transition, bounded

    # -- sampling ---------------------------------------------------------

    def _default_source(self, series):
        from .. import monitor as _mon
        v = _mon.registry().value(series, default=None)
        return v if isinstance(v, (int, float)) else None

    def feed(self, rule_name, value, now=None):
        """Inject one sample for a rule (tests / push-style feeds)."""
        now = time.time() if now is None else now
        rule = next((r for r in self.rules if r.name == rule_name), None)
        if rule is None:
            raise KeyError(rule_name)
        self._append(rule, value, now)

    def _append(self, rule, value, now):
        import collections
        with self._lock:
            dq = self._samples.get(rule.name)
            if dq is None:
                dq = self._samples[rule.name] = collections.deque()
            dq.append((now, bool(rule.breaches(value))))
            horizon = max(rule.fast_window_s, rule.slow_window_s)
            while dq and now - dq[0][0] > horizon:
                dq.popleft()

    def burn_rates(self, rule, now=None):
        """(fast_burn, slow_burn) — breach fraction per window divided
        by budget; None when the window holds no samples yet."""
        now = time.time() if now is None else now
        with self._lock:
            dq = list(self._samples.get(rule.name, ()))
        out = []
        for window in (rule.fast_window_s, rule.slow_window_s):
            sub = [b for t, b in dq if now - t <= window]
            if not sub:
                out.append(None)
                continue
            frac = sum(sub) / len(sub)
            out.append(frac / rule.budget if rule.budget > 0
                       else (float("inf") if frac else 0.0))
        return tuple(out)

    # -- evaluation -------------------------------------------------------

    def tick(self, now=None):
        """One evaluation pass: pull a sample per rule (when a source
        yields one), walk every alert's state machine, age out
        finding-driven alerts the detector stopped reporting. Returns
        the list of currently firing alerts."""
        now = time.time() if now is None else now
        src = self._source or self._default_source
        for rule in self.rules:
            try:
                v = src(rule.series)
            except Exception:
                v = None
            if v is not None:
                self._append(rule, v, now)
            self._evaluate_rule(rule, now)
        self._age_findings(now)
        self._publish(now)
        return self.firing()

    def _evaluate_rule(self, rule, now):
        fast, slow = self.burn_rates(rule, now)
        key = f"rule:{rule.name}"
        alert = self._alerts.get(key)
        fast_hot = fast is not None and fast >= rule.burn_threshold
        slow_hot = slow is not None and slow >= rule.burn_threshold
        ctx = {"fast_burn": fast, "slow_burn": slow,
               "objective": rule.objective,
               "direction": rule.direction,
               "burn_threshold": rule.burn_threshold}
        if alert is None or alert.state == "resolved":
            if fast_hot:
                alert = Alert(rule.name, series=rule.series, context=ctx)
                alert.pending_at = now
                self._alerts[key] = alert
                self._transition(alert, "pending", now)
                if slow_hot:
                    alert.state = "firing"
                    alert.fired_at = now
                    self._transition(alert, "firing", now)
            return
        alert.context.update(ctx)
        if alert.state == "pending":
            if not fast_hot:
                # a blip that never reached the slow window dissolves
                # without ever firing — that's the point of the pattern
                del self._alerts[key]
            elif slow_hot:
                alert.state = "firing"
                alert.fired_at = now
                self._transition(alert, "firing", now)
        elif alert.state == "firing" and not fast_hot:
            alert.state = "resolved"
            alert.resolved_at = now
            self._transition(alert, "resolved", now)

    # -- finding-driven alerts -------------------------------------------

    def raise_finding(self, finding, now=None):
        """Promote an anomaly finding straight to ``firing`` (one alert
        per finding key; re-raising refreshes it). Returns the Alert."""
        now = time.time() if now is None else now
        key = f"finding:{finding['name']}"
        self._finding_seen[key] = now
        alert = self._alerts.get(key)
        if alert is not None and alert.state != "resolved":
            alert.context.update(finding)
            return alert
        alert = Alert(finding["name"], series=finding.get("series"),
                      source=finding.get("source"), context=finding)
        alert.pending_at = alert.fired_at = now
        alert.state = "firing"
        self._alerts[key] = alert
        self._transition(alert, "firing", now)
        return alert

    def _age_findings(self, now):
        for key, alert in list(self._alerts.items()):
            if not key.startswith("finding:") or alert.state != "firing":
                continue
            last = self._finding_seen.get(key, 0.0)
            if now - last > self.finding_resolve_after_s:
                alert.state = "resolved"
                alert.resolved_at = now
                self._transition(alert, "resolved", now)

    # -- bookkeeping ------------------------------------------------------

    def _transition(self, alert, state, now):
        rec = dict(alert.as_dict(), state=state, ts=now)
        self.history.append(rec)
        del self.history[:-200]
        from .. import monitor as _mon
        if _mon.enabled():
            if state == "firing":
                _mon.counter("alerts.fired").inc()
            taken = {"kind", "name", "state", "series", "source", "ts"}
            _mon.emit(kind="alert", name=alert.name, state=state,
                      series=alert.series, source=alert.source,
                      **{k: v for k, v in alert.context.items()
                         if k not in taken
                         and isinstance(v, (int, float, str, bool,
                                            type(None)))})

    def _publish(self, now):
        from .. import monitor as _mon
        if _mon.enabled():
            _mon.gauge("alerts.firing").set(len(self.firing()))

    def alerts(self):
        return [a.as_dict() for a in self._alerts.values()]

    def firing(self):
        return [a.as_dict() for a in self._alerts.values()
                if a.state == "firing"]


# ---------------------------------------------------------------------------
# anomaly detection

def _hist_stats(snap, name):
    h = snap.get("histograms", {}).get(name)
    if not h:
        return None
    return float(h["sum"]), int(h["count"])


class AnomalyDetector:
    """Diffs per-source snapshots tick-over-tick and reports findings
    for the chaos-suite failure shapes. Feed it
    ``FleetAggregator.source_snapshots()`` (or hand-built equivalents)
    via :meth:`update`; it returns the current findings and publishes
    them to :func:`active_findings` (and, when given a ``manager``, as
    firing alerts)."""

    def __init__(self, manager=None, warmup_ticks=2,
                 compile_delta_threshold=3, compile_window_s=3.0,
                 z_threshold=3.0, sigma_floor_frac=0.10, min_sources=3,
                 accept_rate_floor=0.2, queue_ratio=4.0,
                 queue_min_depth=8):
        self.manager = manager
        self.warmup_ticks = int(warmup_ticks)
        self.compile_delta_threshold = int(compile_delta_threshold)
        self.compile_window_s = float(compile_window_s)
        self.z_threshold = float(z_threshold)
        self.sigma_floor_frac = float(sigma_floor_frac)
        self.min_sources = int(min_sources)
        self.accept_rate_floor = float(accept_rate_floor)
        self.queue_ratio = float(queue_ratio)
        self.queue_min_depth = int(queue_min_depth)
        self._ticks = {}        # source -> ticks seen
        self._compiles = {}     # source -> last total compile count
        self._compile_win = {}  # source -> deque[(ts, delta)]
        self._step_hist = {}    # source -> (sum, count) last seen
        self._accept_ok = set()  # sources that were ever healthy
        self.findings = []

    def update(self, snapshots, now=None):
        now = time.time() if now is None else now
        findings = []
        by_source = {}
        for snap in snapshots:
            src = str(snap.get("source"))
            by_source[src] = snap
            self._ticks[src] = self._ticks.get(src, 0) + 1
        findings += self._compile_storms(by_source, now)
        findings += self._stragglers(by_source, now)
        findings += self._accept_collapse(by_source, now)
        findings += self._queue_divergence(by_source, now)
        self.findings = findings
        set_active_findings(findings)
        if self.manager is not None:
            for f in findings:
                self.manager.raise_finding(f, now=now)
        return findings

    # -- the shapes -------------------------------------------------------

    def _compile_storms(self, by_source, now):
        # a real storm's compiles take wall time each, so one burst
        # lands spread across scrape ticks — the verdict sums deltas
        # over compile_window_s, not per tick (an instantaneous burst
        # still trips it: the current delta is in the window)
        import collections
        out = []
        for src, snap in by_source.items():
            counters = snap.get("counters", {})
            per_series = {s: int(counters.get(s, 0))
                          for s in COMPILE_SERIES}
            total = sum(per_series.values())
            prev = self._compiles.get(src)
            self._compiles[src] = total
            if prev is None or self._ticks.get(src, 0) <= self.warmup_ticks:
                continue  # warmup compiles are the plan, not a storm
            win = self._compile_win.setdefault(src, collections.deque())
            delta = total - prev
            if delta > 0:
                win.append((now, delta))
            while win and now - win[0][0] > self.compile_window_s:
                win.popleft()
            windowed = sum(d for _, d in win)
            if windowed >= self.compile_delta_threshold:
                series = max((s for s in COMPILE_SERIES),
                             key=lambda s: per_series[s])
                out.append({"name": f"compile_storm({src})",
                            "kind": "compile_storm", "source": src,
                            "series": series, "delta": windowed,
                            "window_s": self.compile_window_s,
                            "total": total, "ts": now})
        return out

    def _stragglers(self, by_source, now):
        # current-tick mean decode step time per source, from the
        # histogram's sum/count delta since the last tick (lifetime
        # means would dilute a straggler that turned slow mid-run)
        means = {}
        for src, snap in by_source.items():
            cur = _hist_stats(snap, "serving.decode.step_ms")
            if cur is None:
                continue
            prev = self._step_hist.get(src)
            self._step_hist[src] = cur
            if prev is None:
                d_sum, d_count = cur
            else:
                d_sum, d_count = cur[0] - prev[0], cur[1] - prev[1]
            if d_count > 0:
                means[src] = d_sum / d_count
        if len(means) < self.min_sources:
            return []
        out = []
        for src, mean in means.items():
            others = [m for s, m in means.items() if s != src]
            mu = sum(others) / len(others)
            var = sum((m - mu) ** 2 for m in others) / len(others)
            sigma = max(var ** 0.5, self.sigma_floor_frac * mu, 1e-9)
            z = (mean - mu) / sigma
            if z > self.z_threshold:
                out.append({"name": f"straggler({src})",
                            "kind": "straggler", "source": src,
                            "series": "serving.decode.step_ms",
                            "mean_ms": round(mean, 3),
                            "fleet_mean_ms": round(mu, 3),
                            "z": round(z, 2), "ts": now})
        return out

    def _accept_collapse(self, by_source, now):
        out = []
        for src, snap in by_source.items():
            rate = snap.get("gauges", {}).get(
                "serving.decode.accept_rate")
            if rate is None:
                continue
            if rate >= self.accept_rate_floor:
                self._accept_ok.add(src)
            elif src in self._accept_ok:
                out.append({"name": f"accept_collapse({src})",
                            "kind": "accept_collapse", "source": src,
                            "series": "serving.decode.accept_rate",
                            "accept_rate": round(float(rate), 4),
                            "floor": self.accept_rate_floor, "ts": now})
        return out

    def _queue_divergence(self, by_source, now):
        depths = {}
        for src, snap in by_source.items():
            d = snap.get("gauges", {}).get("serving.queue_depth")
            if d is not None:
                depths[src] = float(d)
        if len(depths) < self.min_sources:
            return []
        ordered = sorted(depths.values())
        median = ordered[len(ordered) // 2]
        out = []
        for src, depth in depths.items():
            if (depth >= self.queue_min_depth
                    and depth >= self.queue_ratio * (median + 1.0)):
                out.append({"name": f"queue_divergence({src})",
                            "kind": "queue_divergence", "source": src,
                            "series": "serving.queue_depth",
                            "depth": depth, "fleet_median": median,
                            "ts": now})
        return out


# ---------------------------------------------------------------------------
# the module-global finding board (what the supervisor reads)

_findings_lock = threading.Lock()
_active = {}     # finding name -> finding dict


def set_active_findings(findings):
    """Replace the board with the detector's current view (called by
    :meth:`AnomalyDetector.update` each tick)."""
    with _findings_lock:
        _active.clear()
        for f in findings:
            _active[f["name"]] = dict(f)


def active_findings():
    """The anomalies currently in force, for decision-context citation
    (ServingSupervisor attaches these to its verdicts)."""
    with _findings_lock:
        return list(_active.values())


def clear_findings():
    """Empty the board (test isolation)."""
    with _findings_lock:
        _active.clear()
