"""paddle_tpu.monitor.step — training-loop instrumentation + MFU.

``StepMonitor`` wraps a training loop and reports, per step window:
step time, items/sec (tokens or images), device memory stats
(``jax.local_devices()[i].memory_stats()``), and MFU against a
configurable flops ceiling. Each step emits a JSONL ``step`` record
through the monitor sink, and ``report()`` prints a summary table plus a
final ``counters`` snapshot event — the round's perf ledger rows
(docs/PERF_LEDGER.md) are built from exactly these records.

MFU here is the standard model-flops utilization: model flops per step
(NOT hardware flops — rematerialization and padding don't count) divided
by step time, over the chip's peak. The ceiling resolves, in order:
an explicit ``peak_flops=``, ``PADDLE_TPU_FLOPS_CEILING``, then a
device-kind table of per-chip dense bf16 peaks. Unknown device (e.g. the
CPU test mesh) leaves ``mfu`` null rather than inventing a number.
"""
from __future__ import annotations

import os
import time

# per-chip dense bf16 peak FLOP/s by jax device_kind substring
_PEAK_FLOPS_BF16 = (
    ("TPU v6", 918e12),
    ("TPU v5p", 459e12),
    ("TPU v5 lite", 197e12),
    ("TPU v5e", 197e12),
    ("TPU v4", 275e12),
    ("TPU v3", 123e12),
    ("TPU v2", 46e12),
)

# per-chip HBM bandwidth (GB/s) by the same substrings — the other half
# of the roofline monitor.profile classifies against
_PEAK_HBM_GBPS = (
    ("TPU v6", 1640.0),
    ("TPU v5p", 2765.0),
    ("TPU v5 lite", 819.0),
    ("TPU v5e", 819.0),
    ("TPU v4", 1228.0),
    ("TPU v3", 900.0),
    ("TPU v2", 700.0),
)

_ceilings_cache = {}


def ceilings_for_kind(kind):
    """The single cached (peak_flops, hbm_bytes_per_sec) table lookup
    for a device_kind string; either half is None when the kind is
    unknown. Env overrides live in the callers (peak_flops_for_device,
    profile.roofline_ceilings) so the cache never captures them."""
    kind = str(kind)
    hit = _ceilings_cache.get(kind)
    if hit is None:
        flops = next((p for tag, p in _PEAK_FLOPS_BF16 if tag in kind),
                     None)
        bw = next((b * 1e9 for tag, b in _PEAK_HBM_GBPS if tag in kind),
                  None)
        hit = _ceilings_cache[kind] = (flops, bw)
    return hit

# BERT-base has ~110M params; training flops/token ~= 6N (fwd 2N + bwd 4N)
BERT_BASE_PARAMS = 110e6
# ResNet-50 fwd @224 is ~4.1 GMACs = 8.2 GFLOPs; training ~= 3x fwd
RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 8.2e9


def transformer_train_flops_per_token(n_params):
    """6N flops/token (Kaplan/PaLM accounting: fwd 2N + bwd 4N)."""
    return 6.0 * float(n_params)


def peak_flops_for_device(device=None):
    """Per-chip flops ceiling, or None when the device is unknown.
    PADDLE_TPU_FLOPS_CEILING (flops/s) overrides the table."""
    env = os.environ.get("PADDLE_TPU_FLOPS_CEILING")
    if env:
        return float(env)
    if device is None:
        import jax
        try:
            device = jax.local_devices()[0]
        except Exception:
            return None
    kind = str(getattr(device, "device_kind", ""))
    return ceilings_for_kind(kind)[0]


def peak_hbm_bandwidth_for_device(device=None):
    """Per-chip HBM bandwidth ceiling in bytes/s, or None when unknown.
    PADDLE_TPU_HBM_GBPS (GB/s) overrides the table."""
    env = os.environ.get("PADDLE_TPU_HBM_GBPS")
    if env:
        return float(env) * 1e9
    if device is None:
        import jax
        try:
            device = jax.local_devices()[0]
        except Exception:
            return None
    kind = str(getattr(device, "device_kind", ""))
    return ceilings_for_kind(kind)[1]


def mfu(flops_per_step, step_time_s, peak_flops=None):
    """Model-flops utilization, or None if the ceiling is unknown."""
    peak = peak_flops if peak_flops is not None else peak_flops_for_device()
    if not peak or not flops_per_step or not step_time_s:
        return None
    return flops_per_step / step_time_s / peak


#: the goodput ledger's loss categories: every second of a run that is
#: NOT compute, attributed from series the subsystems already emit
#: (counter values or histogram sums, all in seconds). ``compute`` is
#: the residual — wall time no category claims — so by construction
#: compute + losses reconcile to wall time exactly (the telemetry
#: smoke gate still checks the reconciliation end-to-end, which catches
#: a category double-counting overlapped time).
GOODPUT_CATEGORIES = (
    ("input_stall", ("prefetch.stall_seconds",)),
    ("comm_exposed", ("comm.exposed_wait_s_total",)),
    ("offload_wait", ("mem.offload.exposed_wait_s_total",)),
    ("compile", ("executor.compile_s", "jit.compile_s")),
    ("checkpoint", ("ckpt.save_s",)),
    ("restart_rollback", ("ckpt.restore_s",)),
)


def _series_seconds(reg, name):
    """Seconds held by one series right now: a counter's value, a
    histogram's sum, 0.0 when the series doesn't exist (the subsystem
    never ran)."""
    m = reg.get(name)
    if m is None:
        return 0.0
    if m.kind == "histogram":
        return float(m.sum)
    v = m.value
    return float(v) if v is not None else 0.0


class GoodputLedger:
    """Attributes a run's wall time across :data:`GOODPUT_CATEGORIES`.

    ``begin()`` snapshots every input series; ``finish()`` diffs them
    against the snapshot, subtracts the per-category losses from wall
    time, and reports ``goodput_fraction`` (= compute ÷ wall) plus the
    ranked time-loss table. StepMonitor runs one per monitored loop;
    it is also usable standalone around any timed region::

        ledger = monitor.GoodputLedger().begin()
        ... run ...
        print(ledger.finish()["goodput_fraction"])
    """

    def __init__(self, registry=None):
        if registry is None:
            from .. import monitor as _mon
            registry = _mon.registry()
        self._reg = registry
        self._t0 = None
        self._base = None

    def _read(self):
        return {name: _series_seconds(self._reg, name)
                for _, series in GOODPUT_CATEGORIES for name in series}

    def begin(self):
        self._t0 = time.perf_counter()
        self._base = self._read()
        return self

    def finish(self, wall_s=None):
        """The ledger dict: wall/compute seconds, goodput fraction, and
        ``lost`` — one row per category with attributed seconds, ranked
        worst-first (zero-loss categories included, at the tail: "this
        was measured and clean" reads differently from "not measured")."""
        if self._t0 is None:
            raise RuntimeError("GoodputLedger.finish() before begin()")
        wall = (time.perf_counter() - self._t0
                if wall_s is None else float(wall_s))
        cur = self._read()
        base = self._base
        lost = []
        for category, series in GOODPUT_CATEGORIES:
            seconds = sum(cur[n] - base[n] for n in series)
            seconds = max(0.0, seconds)
            lost.append({"category": category,
                         "seconds": round(seconds, 6),
                         "fraction": (round(seconds / wall, 4)
                                      if wall > 0 else None),
                         "series": list(series)})
        lost.sort(key=lambda row: -row["seconds"])
        total_lost = sum(row["seconds"] for row in lost)
        compute = max(0.0, wall - total_lost)
        out = {"wall_s": round(wall, 6),
               "compute_s": round(compute, 6),
               "lost_s": round(total_lost, 6),
               "goodput_fraction": (round(compute / wall, 4)
                                    if wall > 0 else None),
               "lost": lost}
        from . import emit, enabled, gauge
        if enabled():
            if out["goodput_fraction"] is not None:
                gauge("goodput.fraction").set(out["goodput_fraction"])
            for row in lost:
                gauge(f"goodput.lost_s.{row['category']}").set(
                    row["seconds"])
            emit(kind="goodput", **out)
        return out


_mem_stats_warned = False


def device_memory_stats():
    """bytes_in_use / peak_bytes_in_use / bytes_limit per local device.
    A backend that exposes nothing (CPU's ``memory_stats()`` returns
    None; some return dicts missing the HBM keys) contributes an EMPTY
    per-device dict instead of being dropped or raising — callers can
    still enumerate devices, and the degradation is warned exactly once
    per process."""
    global _mem_stats_warned
    import jax
    out = {}
    try:
        devices = jax.local_devices()
    except Exception:
        return out
    for d in devices:
        entry = {}
        try:
            stats = d.memory_stats()
            if stats:
                entry = {k: stats[k]
                         for k in ("bytes_in_use", "peak_bytes_in_use",
                                   "bytes_limit") if k in stats}
        except Exception:
            entry = {}
        if not entry and not _mem_stats_warned:
            _mem_stats_warned = True
            import warnings
            try:
                backend = jax.default_backend()
            except Exception:
                backend = "?"
            warnings.warn(
                f"device_memory_stats: backend '{backend}' platform "
                f"'{getattr(d, 'platform', '?')}' device {d} "
                f"({getattr(d, 'device_kind', '?')}) exposes no memory "
                "stats (expected on CPU backends); its entries will be "
                "empty dicts")
        out[str(d.id)] = entry
    return out


class StepMonitor:
    """Wraps a training loop:

        mon = monitor.StepMonitor(items_per_step=batch * seq,
                                  flops_per_step=6 * n_params * batch * seq,
                                  item="tokens", label="bert")
        for batch in loader:
            loss = train_step(batch)
            mon.step(loss=loss)
        mon.report()

    ``step()`` stamps the wall-clock since the previous step (call it
    AFTER the device sync your loop already does — an async dispatch
    makes any host timer lie), updates throughput/mfu gauges, and emits
    one JSONL ``step`` record per ``window`` steps (default every step).
    """

    def __init__(self, items_per_step=None, flops_per_step=None,
                 peak_flops=None, item="items", label="train", window=1,
                 memory_every=10, measured_flops_per_step=None,
                 xla_label=None, goodput=True):
        self.items_per_step = items_per_step
        self.flops_per_step = flops_per_step
        self.peak_flops = (peak_flops if peak_flops is not None
                           else peak_flops_for_device())
        self.item = item
        self.label = label
        self.window = max(1, int(window))
        self.memory_every = max(1, int(memory_every))
        # XLA-measured flops: explicit value, or pulled per step from
        # monitor.xla (xla_label=None means "most recently captured
        # executable" — right for a loop driving one compiled step)
        self.measured_flops_per_step = measured_flops_per_step
        self.xla_label = xla_label
        self.steps = 0
        self.total_time = 0.0
        self.records = []
        self._last = None
        self._divergence_warned = False
        self._mem_peaks = {}     # device id -> last seen peak watermark
        # the goodput ledger (category definitions above): armed at
        # start(), settled at report() — two registry reads per run
        self._goodput = GoodputLedger() if goodput else None
        self._goodput_report = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.report()

    def start(self):
        self._last = time.perf_counter()
        if self._goodput is not None:
            self._goodput.begin()
        return self

    def step(self, items=None, loss=None, **extra):
        """Mark one completed step; returns the record dict."""
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            # a loop that skipped start() still gets a ledger window
            # (first step marks its opening edge)
            if self._goodput is not None and self._goodput._t0 is None:
                self._goodput.begin()
            return None
        dt = now - self._last
        self._last = now
        self.steps += 1
        self.total_time += dt

        items = items if items is not None else self.items_per_step
        rate = (items / dt) if (items and dt > 0) else None
        step_mfu = mfu(self.flops_per_step, dt, self.peak_flops)

        from . import emit, enabled, gauge
        rec = {"kind": "step", "label": self.label, "step": self.steps,
               "step_time_s": round(dt, 6),
               f"{self.item}_per_sec": round(rate, 2) if rate else None,
               "items_per_sec": round(rate, 2) if rate else None,
               "mfu": round(step_mfu, 4) if step_mfu is not None else None}
        measured = self._measured_flops()
        mfu_measured = None
        if measured:
            mfu_measured = mfu(measured, dt, self.peak_flops)
            if mfu_measured is not None:
                rec["mfu_measured"] = round(mfu_measured, 4)
            if self.flops_per_step:
                ratio = measured / self.flops_per_step
                if abs(ratio - 1.0) > 0.2:
                    # the analytic convention and XLA's count disagree
                    # by >20% — one of them is lying; say so once
                    rec["flops_measured_ratio"] = round(ratio, 3)
                    if not self._divergence_warned:
                        self._divergence_warned = True
                        import warnings
                        warnings.warn(
                            f"StepMonitor[{self.label}]: XLA-measured "
                            f"flops/step ({measured:.3e}) diverges "
                            f"{(ratio - 1.0):+.0%} from the analytic "
                            f"figure ({self.flops_per_step:.3e}); the "
                            f"reported mfu uses the analytic number")
                        if enabled():
                            from . import counter
                            counter("xla.mfu_divergence").inc()
        if loss is not None:
            try:
                rec["loss"] = float(loss.numpy() if hasattr(loss, "numpy")
                                    else loss)
            except Exception:
                pass
        rec.update(extra)
        if self.steps % self.memory_every == 0 or self.steps == 1:
            mem = device_memory_stats()
            if any(mem.values()):  # all-empty dicts (CPU) stay out
                rec["device_memory"] = mem
                # the delta since the last sampled step is the signal
                # (a watermark that keeps climbing is a leak; a raw
                # snapshot alone can't show that)
                deltas = {}
                for did, stats in mem.items():
                    peak = stats.get("peak_bytes_in_use")
                    if peak is None:
                        continue
                    prev = self._mem_peaks.get(did)
                    if prev is not None:
                        deltas[did] = peak - prev
                    self._mem_peaks[did] = peak
                if deltas:
                    rec["device_memory_peak_delta"] = deltas
        self.records.append(rec)
        if enabled():
            gauge(f"step.{self.label}.time_s").set(dt)
            if rate:
                gauge(f"step.{self.label}.items_per_sec").set(rate)
            if step_mfu is not None:
                gauge(f"step.{self.label}.mfu").set(step_mfu)
            if mfu_measured is not None:
                gauge(f"step.{self.label}.mfu_measured").set(mfu_measured)
            if self.steps % self.window == 0:
                emit(**rec)
        return rec

    def _measured_flops(self):
        """XLA-counted flops/step: the explicit override, else the
        monitor.xla capture for xla_label (None -> newest)."""
        if self.measured_flops_per_step is not None:
            return self.measured_flops_per_step
        from . import xla as _xla
        return _xla.flops(self.xla_label)

    def _settle_goodput(self):
        """Finish the ledger exactly once (summary() and report() both
        want it; a second finish would re-window nothing)."""
        if (self._goodput is not None and self._goodput._t0 is not None
                and self._goodput_report is None):
            self._goodput_report = self._goodput.finish()
        return self._goodput_report

    # -- summary ------------------------------------------------------------
    def summary(self):
        if not self.steps:
            return {"label": self.label, "steps": 0}
        avg_dt = self.total_time / self.steps
        rate = (self.items_per_step / avg_dt
                if self.items_per_step and avg_dt > 0 else None)
        out = {
            "label": self.label, "steps": self.steps,
            "avg_step_time_s": round(avg_dt, 6),
            f"{self.item}_per_sec": round(rate, 2) if rate else None,
            "mfu": (round(mfu(self.flops_per_step, avg_dt,
                              self.peak_flops), 4)
                    if mfu(self.flops_per_step, avg_dt,
                           self.peak_flops) is not None else None),
            "peak_flops_ceiling": self.peak_flops,
        }
        measured = self._measured_flops()
        if measured:
            m = mfu(measured, avg_dt, self.peak_flops)
            if m is not None:
                out["mfu_measured"] = round(m, 4)
            out["flops_per_step_measured"] = measured
        g = self._settle_goodput()
        if g is not None:
            out["goodput"] = g
        return out

    def report(self, print_table=True):
        """Print the summary table and emit it (plus a full counters
        snapshot) to the JSONL sink; returns the summary dict."""
        s = self.summary()
        if print_table and self.steps:
            rate = s.get(f"{self.item}_per_sec")
            rows = [("steps", s["steps"]),
                    ("avg step time", f"{s['avg_step_time_s'] * 1e3:.2f} ms"),
                    (f"{self.item}/sec", f"{rate:,.1f}" if rate else "n/a"),
                    ("mfu", f"{s['mfu']:.1%}" if s["mfu"] is not None
                     else "n/a (no flops ceiling)")]
            if s.get("mfu_measured") is not None:
                rows.append(("mfu (xla-measured)",
                             f"{s['mfu_measured']:.1%}"))
            g = s.get("goodput")
            if g is not None and g.get("goodput_fraction") is not None:
                rows.append(("goodput", f"{g['goodput_fraction']:.1%}"))
                for row in g["lost"][:3]:
                    if row["seconds"] > 0:
                        rows.append((f"  lost: {row['category']}",
                                     f"{row['seconds'] * 1e3:.1f} ms "
                                     f"({row['fraction']:.1%})"))
            width = max(len(k) for k, _ in rows)
            print(f"[paddle_tpu.monitor] {self.label}")
            for k, v in rows:
                print(f"  {k:<{width}}  {v}")
        from . import emit, enabled, snapshot
        if enabled():
            emit(kind="step_summary", **s)
            emit(kind="counters", counters=snapshot())
        return s
