"""paddle_tpu.monitor.memory — HBM buffer liveness, peak attribution,
and OOM forensics.

``monitor.profile`` (PR 9) answers "which layer owns the flops";
this module answers the question ROADMAP item 4 calls first-class —
*which layer owns the peak HBM, and will this layout even fit?* It
walks the **scheduled** instruction stream of a captured executable's
optimized HLO (``is_scheduled=true`` — text order IS the schedule),
assigns every top-level buffer a (def, last-use) interval and a size
from its shape/dtype, and simulates occupancy over the schedule:

* ``predicted_peak_bytes`` — the simulated high-water mark, following
  XLA's own ``memory_analysis()`` accounting (arguments resident for
  the whole execution, non-aliased outputs live to the end, donated
  input/output pairs counted once via the module's
  ``input_output_alias`` map, fusion-internal temps excluded because
  only the top-level stream allocates). Reconciled against
  ``Compiled.memory_analysis()`` peak (``xla.peak_memory.<label>``)
  and the sampler's live ``mem.device.*.peak_bytes_in_use`` watermark.
* a ranked **peak-contributor ledger** — the buffers live at the peak
  instant, attributed to framework scopes through the ``profile``
  scope registry and classified ``param`` / ``activation`` /
  ``opt_state`` / ``temp``.
* a **memory-over-time curve**, exported as Chrome-trace ``"C"``
  counter events on its own track (``trace.counter``), so Perfetto
  shows predicted HBM occupancy under the span timeline.

Two loops close on this model: ``parallel.planner.advise()`` calls
:func:`device_hbm_limit` to mark over-budget layouts infeasible
(the pre-flight budget report), and the Executor/``hapi.fit`` crash
handlers call :func:`handle_oom` so every RESOURCE_EXHAUSTED leaves a
flight-recorder dump bundling this report next to the op ledger.

Cost discipline: nothing here runs until :func:`report` (or an OOM)
— the liveness model is a pure post-hoc parse of HLO text that was
captured anyway, and ``is_oom_error`` is only consulted on the crash
path. All CPU-runnable: HLO + memory_analysis need no TPU.

Usage::

    from paddle_tpu import monitor
    monitor.enable(); monitor.profile.enable()
    ... one jitted train step (aot-captured by monitor.xla) ...
    rep = monitor.memory.report()          # structured dict
    print(monitor.memory.format_table(rep))
"""
from __future__ import annotations

import os
import re
import time

from . import profile as _profile

__all__ = [
    "parse_io_alias", "liveness", "simulate", "report", "last_report",
    "last_summary", "format_table", "curve_counter_events",
    "device_hbm_limit", "is_oom_error", "handle_oom", "last_oom",
    "reset", "CLASSES",
]

CLASSES = ("param", "activation", "opt_state", "temp", "remat")

# HLO op-name markers jax.checkpoint leaves on the backward's replay
# (the primal forward keeps plain scope names — only recomputation is
# tagged): the jvp(checkpoint) transpose path and remat2's
# rematted_computation sub-scope. Buffers born under either are
# recomputed activations, not stored ones.
_REMAT_NAME_RE = re.compile(
    r"rematted_computation|remat2|jvp\(checkpoint\)")

# view opcodes: they alias operand storage, never allocate
_TUPLE_OPS = frozenset(("tuple",))
_GTE_OPS = frozenset(("get-tuple-element",))
_ALIAS_OPS = frozenset(("bitcast", "after-all", "optimization-barrier"))
# while writes its state in place: output aliases the operand tuple
_INPLACE_OPS = frozenset(("while",))
# no backing buffer at runtime (constants live in the executable image,
# outside the argument/output/temp accounting this model mirrors)
_NO_BUFFER_OPS = frozenset(("constant", "partition-id", "replica-id"))

_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)\s*$")
_GTE_INDEX_RE = re.compile(r"index=(\d+)")
_ALIAS_PAIR_RE = re.compile(r"\{\s*(\d+)[^}]*\}\s*:\s*\(\s*(\d+)")

_last = None            # cached last report() result
_last_oom = None        # {"ts","path","where","step","error"} of last OOM


# ---------------------------------------------------------------------------
# HLO module header: donated input/output pairs

def parse_io_alias(text):
    """The ``input_output_alias={ {out}: (param, ...), ... }`` map from
    the HloModule header line -> {output_tuple_index: param_number}.
    Empty dict when the module declares no aliasing (no donation)."""
    head = text.find("input_output_alias=")
    if head < 0:
        return {}
    brace = text.find("{", head)
    if brace < 0:
        return {}
    end = _profile._balanced(text, brace, "{", "}")
    body = text[brace + 1:end - 1]
    out = {}
    for om, pm in _ALIAS_PAIR_RE.findall(body):
        out[int(om)] = int(pm)
    return out


def _operand_name(operand):
    m = _OPERAND_NAME_RE.search(operand)
    return m.group(1) if m else None


# ---------------------------------------------------------------------------
# the liveness model

def liveness(text, scope_map=None):
    """Buffer intervals over the scheduled entry computation.

    Returns ``{"buffers": {name: row}, "schedule_len": N,
    "alias_map": {...}}`` or None when the text has no entry. Each row:
    ``size`` (bytes), ``def_idx`` / ``last_use`` (schedule indices,
    inclusive), ``space`` ("argument" / "output" / "temp"),
    ``donated`` (output written in place into a donated argument —
    contributes no bytes of its own), ``region`` / ``scope_kind``
    (profile-registry attribution, with a first-scoped-consumer
    fallback for unlabeled buffers like parameters and copies), and
    ``klass`` (param / activation / opt_state / temp).

    Only the top-level stream allocates: fusion bodies, folded
    ``to_apply`` reducers and while bodies are internal to their
    calling instruction, so their temps never appear — exactly XLA's
    buffer-assignment view. ``tuple`` / ``get-tuple-element`` /
    ``bitcast`` are views; ``while`` aliases its operand tuple in
    place."""
    scope_map = (dict(_profile._scopes) if scope_map is None
                 else dict(scope_map))
    comps, entry, _refs = _profile.parse_hlo(text)
    if entry is None:
        return None
    instrs = comps[entry]["instrs"]
    n = len(instrs)
    alias_map = parse_io_alias(text)

    buffers = {}     # name -> row
    views = {}       # name -> ("tuple", [members]) | ("gte", src, idx)
    #                          | ("alias", [srcs])
    root = None

    for i, ins in enumerate(instrs):
        op, name = ins["opcode"], ins["name"]
        if ins.get("root"):
            root = ins
        if op == "parameter":
            try:
                pnum = int(ins["operands"][0])
            except (ValueError, IndexError):
                pnum = -1
            buffers[name] = {
                "name": name, "opcode": op, "op_name": ins["op_name"],
                "size": _profile._type_bytes(ins["out_type"]),
                "def_idx": 0, "last_use": n - 1, "space": "argument",
                "pnum": pnum, "donated": False,
                "consumer_region": None, "consumer_kinds": set(),
            }
        elif op in _NO_BUFFER_OPS:
            pass
        elif op in _TUPLE_OPS:
            views[name] = ("tuple",
                           [_operand_name(o) for o in ins["operands"]])
        elif op in _GTE_OPS:
            gm = _GTE_INDEX_RE.search(ins["attrs"])
            views[name] = ("gte",
                           _operand_name(ins["operands"][0])
                           if ins["operands"] else None,
                           int(gm.group(1)) if gm else 0)
        elif op in _ALIAS_OPS or op in _INPLACE_OPS:
            views[name] = ("alias",
                           [_operand_name(o) for o in ins["operands"]])
        else:
            buffers[name] = {
                "name": name, "opcode": op, "op_name": ins["op_name"],
                "size": _profile._type_bytes(ins["out_type"]),
                "def_idx": i, "last_use": i, "space": "temp",
                "pnum": None, "donated": False,
                "consumer_region": None, "consumer_kinds": set(),
            }

    def _tuple_members(src, depth=0):
        # follow alias/while chains to a concrete tuple view's members
        while src is not None and depth < 64:
            depth += 1
            if src in buffers:
                return None
            v = views.get(src)
            if v is None:
                return None
            if v[0] == "tuple":
                return v[1]
            src = v[1][0] if (v[0] == "alias" and v[1]) else (
                v[1] if v[0] == "gte" else None)
        return None

    def _resolve(name, depth=0):
        """Concrete buffer names a reference ultimately reads."""
        if name is None or depth > 64:
            return []
        if name in buffers:
            return [name]
        v = views.get(name)
        if v is None:
            return []
        if v[0] == "tuple":
            out = []
            for m in v[1]:
                out.extend(_resolve(m, depth + 1))
            return out
        if v[0] == "gte":
            members = _tuple_members(v[1])
            if members is not None and 0 <= v[2] < len(members):
                return _resolve(members[v[2]], depth + 1)
            return _resolve(v[1], depth + 1)
        out = []
        for m in v[1]:
            out.extend(_resolve(m, depth + 1))
        return out

    # uses: every operand reference extends the underlying buffers'
    # lifetimes; the first *scoped* consumer also donates attribution
    # to unlabeled buffers (parameters, compiler-inserted copies)
    for i, ins in enumerate(instrs):
        if ins["opcode"] == "parameter":
            continue
        region, leaf = _profile._region_of(ins["op_name"], scope_map)
        kind = scope_map.get(leaf) if leaf else None
        for opnd in ins["operands"]:
            ref = _operand_name(opnd)
            if ref is None:
                continue
            for b in _resolve(ref):
                row = buffers[b]
                if i > row["last_use"] and row["space"] != "argument":
                    row["last_use"] = i
                if kind:
                    row["consumer_kinds"].add(kind)
                    if row["consumer_region"] is None and \
                            region != _profile.UNATTRIBUTED:
                        row["consumer_region"] = (region, leaf)

    # outputs: ROOT tuple components live to the end of the schedule;
    # a component aliased to a donated parameter is written *in place*
    # into the argument buffer, so it contributes no bytes of its own
    if root is not None:
        if root["opcode"] in _TUPLE_OPS:
            out_refs = [_operand_name(o) for o in root["operands"]]
        else:
            out_refs = [root["name"]]
        for j, ref in enumerate(out_refs):
            for b in _resolve(ref):
                row = buffers[b]
                if j in alias_map:
                    if row["space"] != "argument":
                        row["donated"] = True
                else:
                    if row["space"] != "argument":
                        row["space"] = "output"
                    row["last_use"] = n - 1

    # attribution + class
    for row in buffers.values():
        region, leaf = _profile._region_of(row["op_name"], scope_map)
        if region == _profile.UNATTRIBUTED and row["consumer_region"]:
            region, leaf = row["consumer_region"]
        row["region"] = region
        row["scope"] = leaf
        row["scope_kind"] = scope_map.get(leaf) if leaf else None
        row["klass"] = _classify(row)
        del row["consumer_region"]
        row["consumer_kinds"] = sorted(row["consumer_kinds"])
    return {"buffers": buffers, "schedule_len": n,
            "alias_map": alias_map}


def _classify(row):
    """param / activation / opt_state / temp / remat for one buffer
    row. remat = an activation recomputed inside a jax.checkpoint
    replay — split out so a rematerialized step's by-class report stays
    honest about what is stored state vs transient recompute."""
    if row["space"] == "argument":
        # jit.to_static labels entry params "state_vals[k]"/"arrays[k]";
        # data arrays are input activations, not weights
        if row["op_name"].startswith("arrays"):
            return "activation"
        kinds = row["consumer_kinds"]
        if kinds and all(k == "optimizer" for k in kinds):
            return "opt_state"
        return "param"
    if _REMAT_NAME_RE.search(row["op_name"]):
        return "remat"
    if row["scope_kind"] == "optimizer":
        return "opt_state"
    if row["scope_kind"] in ("layer", "functional", "op"):
        return "activation"
    return "temp"


# ---------------------------------------------------------------------------
# occupancy simulation

def simulate(text, scope_map=None, top_k=10):
    """Liveness + occupancy over the schedule. Returns the full
    simulation dict (no xla/monitor coupling — pure text in, dict out):
    ``predicted_peak_bytes``, ``peak_index``, ``curve`` (occupancy per
    schedule slot), the byte split (``argument_bytes`` /
    ``output_bytes`` / ``donated_bytes`` / ``temp_peak_bytes``), the
    ranked ``contributors`` ledger (top_k live-at-peak buffers),
    ``by_class`` byte totals at peak, and ``attributed_frac`` — the
    fraction of live-at-peak bytes credited to a registered scope."""
    live = liveness(text, scope_map=scope_map)
    if live is None:
        return None
    n = live["schedule_len"]
    deltas = [0] * (n + 1)
    arg_bytes = out_bytes = donated_bytes = 0
    for row in live["buffers"].values():
        size = row["size"]
        if row["space"] == "argument":
            arg_bytes += size
        elif row["donated"]:
            donated_bytes += size
            continue
        elif row["space"] == "output":
            out_bytes += size
        if size <= 0:
            continue
        deltas[row["def_idx"]] += size
        if row["last_use"] + 1 <= n:
            deltas[row["last_use"] + 1] -= size
    curve, cur = [], 0
    for i in range(n):
        cur += deltas[i]
        curve.append(cur)
    peak = max(curve) if curve else 0
    peak_idx = curve.index(peak) if curve else 0

    contributors, live_total, attributed = [], 0, 0
    by_class = dict.fromkeys(CLASSES, 0)
    for row in live["buffers"].values():
        if row["donated"] or row["size"] <= 0:
            continue
        if not (row["def_idx"] <= peak_idx <= row["last_use"]):
            continue
        live_total += row["size"]
        by_class[row["klass"]] = by_class.get(row["klass"], 0) \
            + row["size"]
        if row["region"] != _profile.UNATTRIBUTED:
            attributed += row["size"]
        contributors.append({
            "name": row["name"], "opcode": row["opcode"],
            "bytes": row["size"], "class": row["klass"],
            "region": row["region"], "scope_kind": row["scope_kind"],
            "space": row["space"], "def_idx": row["def_idx"],
            "last_use": row["last_use"],
        })
    contributors.sort(key=lambda c: (-c["bytes"], c["name"]))
    for rank, c in enumerate(contributors, start=1):
        c["rank"] = rank
    return {
        "schedule_len": n,
        "predicted_peak_bytes": float(peak),
        "peak_index": peak_idx,
        "argument_bytes": float(arg_bytes),
        "output_bytes": float(out_bytes),
        "donated_bytes": float(donated_bytes),
        "temp_peak_bytes": float(peak - arg_bytes - out_bytes)
        if peak else 0.0,
        "curve": curve,
        "live_at_peak_bytes": float(live_total),
        "attributed_bytes": float(attributed),
        "attributed_frac": (attributed / live_total) if live_total
        else 0.0,
        "by_class": by_class,
        "contributors": contributors[:max(0, int(top_k))],
        "n_buffers": len(live["buffers"]),
        "n_donated": sum(1 for r in live["buffers"].values()
                         if r["donated"]),
    }


# ---------------------------------------------------------------------------
# the report (xla reconciliation + monitor emission)

def report(label=None, top_k=10, hlo=None, emit_records=True):
    """Build the memory report for a captured executable.

    ``label`` picks a ``monitor.xla`` capture (default: newest);
    ``hlo=`` simulates a raw HLO string instead. Adds to the pure
    simulation: ``xla_peak_bytes`` (from ``memory_analysis()``) and
    the ``reconciliation`` ratio predicted/xla, plus
    ``measured_peak_bytes`` — the live sampler watermark
    (max ``peak_bytes_in_use`` across devices, None on backends that
    expose nothing, e.g. CPU). Emits
    ``memory.predicted_peak_bytes.<label>`` /
    ``memory.attributed_frac.<label>`` gauges, one ``memory_report``
    JSONL record, and — when span tracing is live — the occupancy
    curve as Chrome ``"C"`` counter events on an ``hbm`` track.
    Returns None when nothing has been captured."""
    global _last
    from . import xla as _xla
    xla_peak = None
    if hlo is None:
        exe = _xla.executable(label)
        if exe is None:
            return None
        if label is None:
            newest = _xla.last()
            label = newest[0] if newest else None
        try:
            hlo = exe.as_text()
        except Exception:
            return None
        xla_peak = _xla.peak_memory(label)
    sim = simulate(hlo, top_k=top_k)
    if sim is None:
        return None
    measured = None
    try:
        from .step import device_memory_stats
        stats = device_memory_stats()
        peaks = [s["peak_bytes_in_use"] for s in stats.values()
                 if "peak_bytes_in_use" in s]
        measured = float(max(peaks)) if peaks else None
    except Exception:
        measured = None
    rep = dict(sim)
    rep.update({
        "kind": "memory_report",
        "ts": time.time(),
        "label": label,
        "xla_peak_bytes": xla_peak,
        "reconciliation": (sim["predicted_peak_bytes"] / xla_peak
                           if xla_peak else None),
        "measured_peak_bytes": measured,
        "hbm_limit_bytes": device_hbm_limit(),
    })
    _last = rep
    from . import emit, enabled as _mon_enabled, gauge
    from . import trace as _trace
    if emit_records and _mon_enabled():
        gauge(f"memory.predicted_peak_bytes.{label}").set(
            rep["predicted_peak_bytes"])
        gauge(f"memory.attributed_frac.{label}").set(
            rep["attributed_frac"])
        emit(kind="memory_report", label=label,
             predicted_peak_bytes=rep["predicted_peak_bytes"],
             xla_peak_bytes=xla_peak,
             reconciliation=rep["reconciliation"],
             measured_peak_bytes=measured,
             attributed_frac=rep["attributed_frac"],
             by_class=rep["by_class"],
             contributors=[
                 {"rank": c["rank"], "bytes": c["bytes"],
                  "class": c["class"], "region": c["region"]}
                 for c in rep["contributors"][:top_k]])
    if emit_records and _trace.enabled():
        for name, values, ts in curve_counter_events(rep):
            _trace.counter(name, values, ts=ts)
    return rep


def curve_counter_events(rep, max_points=512):
    """The occupancy curve as ``(name, values, ts)`` triples for
    ``trace.counter`` — one synthetic microsecond per schedule slot on
    an ``hbm.predicted[<label>]`` counter track, decimated to at most
    ``max_points`` samples (peak-preserving: the decimation keeps each
    window's max)."""
    curve = rep.get("curve") or []
    if not curve:
        return []
    label = rep.get("label") or "hlo"
    name = f"hbm.predicted[{label}]"
    n = len(curve)
    stride = max(1, (n + max_points - 1) // max_points)
    t0 = time.perf_counter()
    out = []
    for start in range(0, n, stride):
        window = curve[start:start + stride]
        out.append((name, {"bytes": max(window)},
                    t0 + start * 1e-6))
    return out


def last_report():
    """The most recent report() result (full ledger), or None."""
    return _last


def last_summary(top_k=3):
    """Compact view of the last report for /snapshot: predicted vs
    measured peak, reconciliation, and the top-k contributors."""
    rep = _last
    if rep is None:
        return None
    return {
        "label": rep["label"],
        "ts": rep["ts"],
        "predicted_peak_bytes": rep["predicted_peak_bytes"],
        "xla_peak_bytes": rep["xla_peak_bytes"],
        "reconciliation": (round(rep["reconciliation"], 4)
                           if rep["reconciliation"] else None),
        "measured_peak_bytes": rep["measured_peak_bytes"],
        "attributed_frac": round(rep["attributed_frac"], 4),
        "by_class": rep["by_class"],
        "contributors": [
            {"rank": c["rank"], "bytes": c["bytes"],
             "class": c["class"], "region": c["region"]}
            for c in rep["contributors"][:top_k]
        ],
    }


def reset():
    """Clear the cached report and the last-OOM pointer."""
    global _last, _last_oom
    _last = None
    _last_oom = None


# ---------------------------------------------------------------------------
# the device HBM budget (planner's feasibility limit)

# per-chip HBM capacity (GiB) by jax device_kind substring — the
# budget line planner.advise() draws; override with
# PADDLE_TPU_HBM_LIMIT_BYTES (bytes) or PADDLE_TPU_HBM_GB
_HBM_CAPACITY_GIB = (
    ("TPU v6", 32.0),
    ("TPU v5p", 95.0),
    ("TPU v5 lite", 16.0),
    ("TPU v5e", 16.0),
    ("TPU v4", 32.0),
    ("TPU v3", 16.0),
    ("TPU v2", 8.0),
)


def device_hbm_limit(device_kind=None):
    """Per-device HBM budget in bytes, or None when unknowable.
    Resolution order: $PADDLE_TPU_HBM_LIMIT_BYTES, $PADDLE_TPU_HBM_GB,
    the backend's live ``bytes_limit``, then the capacity table by
    device kind (CPU stays None — no budget means no infeasibility
    verdicts, never an invented one)."""
    env = os.environ.get("PADDLE_TPU_HBM_LIMIT_BYTES")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    env = os.environ.get("PADDLE_TPU_HBM_GB")
    if env:
        try:
            return float(env) * (1 << 30)
        except ValueError:
            pass
    kind = device_kind
    if kind is None:
        try:
            from .step import device_memory_stats
            limits = [s["bytes_limit"]
                      for s in device_memory_stats().values()
                      if "bytes_limit" in s]
            if limits:
                return float(max(limits))
        except Exception:
            pass
        try:
            import jax
            kind = str(getattr(jax.local_devices()[0],
                               "device_kind", ""))
        except Exception:
            kind = ""
    kind = str(kind)
    for tag, gib in _HBM_CAPACITY_GIB:
        if tag in kind:
            return gib * (1 << 30)
    return None


# ---------------------------------------------------------------------------
# OOM forensics

_OOM_RE = re.compile(
    r"RESOURCE[ _]?EXHAUSTED|out of memory|\bOOM\b|"
    r"[Aa]llocation .* exceeds|failed to allocate", re.IGNORECASE)


def is_oom_error(exc):
    """True when an exception (or anything in its cause/context chain)
    is OOM-shaped: XLA's RESOURCE_EXHAUSTED, an allocator "out of
    memory", or Python's MemoryError."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, MemoryError):
            return True
        try:
            if _OOM_RE.search(str(exc)):
                return True
        except Exception:
            pass
        exc = getattr(exc, "__cause__", None) or \
            getattr(exc, "__context__", None)
    return False


def handle_oom(exc, where, step=None):
    """The crash-path hook Executor.run / hapi.fit / jit call on any
    exception: when ``exc`` is OOM-shaped, build (or reuse) the memory
    report and fire ``flight_record("oom")`` so the dump bundles the
    contributor ledger next to the op ledger + HLO. Returns the flight
    directory, or None (not an OOM, rate-capped, or anything failed —
    forensics must never add a second crash)."""
    global _last_oom
    if not is_oom_error(exc):
        return None
    try:
        if _last is None:
            report(emit_records=False)
    except Exception:
        pass
    try:
        from . import trace as _trace
        extra = {"where": str(where), "error": str(exc)[:500]}
        summary = last_summary()
        if summary:
            extra["memory"] = summary
        path = _trace.flight_record("oom", step=step, extra=extra)
        _last_oom = {"ts": time.time(), "path": path,
                     "where": str(where), "step": step,
                     "error": str(exc)[:200]}
        from . import counter, enabled as _mon_enabled
        if _mon_enabled():
            counter("memory.oom").inc()
        return path
    except Exception:
        return None


def last_oom():
    """{"ts", "path", "where", "step", "error"} of the most recent
    OOM this process handled, or None — /snapshot's pointer."""
    return _last_oom


# ---------------------------------------------------------------------------
# human-readable table

def _fmt_bytes(v):
    if v is None:
        return "n/a"
    for unit, scale in (("GiB", 1 << 30), ("MiB", 1 << 20),
                        ("KiB", 1 << 10)):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{unit}"
    return f"{v:.0f}B"


def format_table(rep, top_k=10):
    """Human-readable peak-contributor ledger for a report() dict."""
    if not rep:
        return "memory: no captured executable"
    lines = [
        f"memory: {rep.get('label') or '<hlo>'}  "
        f"predicted peak {_fmt_bytes(rep['predicted_peak_bytes'])}"
        + (f"  (xla {_fmt_bytes(rep['xla_peak_bytes'])}, "
           f"recon {rep['reconciliation']:.3f})"
           if rep.get("xla_peak_bytes") else "")
        + (f"  measured {_fmt_bytes(rep['measured_peak_bytes'])}"
           if rep.get("measured_peak_bytes") else ""),
        f"  live at peak {_fmt_bytes(rep['live_at_peak_bytes'])} "
        f"(attributed {rep['attributed_frac']:.1%})  "
        + "  ".join(f"{k}={_fmt_bytes(v)}"
                    for k, v in rep["by_class"].items() if v),
        "",
        f"  {'#':>2} {'bytes':>10} {'class':<11} {'space':<9} "
        f"{'region':<40} {'live':<13}",
    ]
    for c in rep["contributors"][:top_k]:
        lines.append(
            f"  {c['rank']:>2} {_fmt_bytes(c['bytes']):>10} "
            f"{c['class']:<11} {c['space']:<9} {c['region'][:40]:<40} "
            f"[{c['def_idx']},{c['last_use']}]")
    return "\n".join(lines)
