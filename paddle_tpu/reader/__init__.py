"""paddle_tpu.reader — the composable reader algebra.

TPU-native rebuild of the reference's reader decorators
(reference: python/paddle/reader/decorator.py — cache:36, map_readers:60,
shuffle:102, chain:151, compose:216, buffered:276, firstn:319,
xmap_readers:364, multiprocess_reader:457; and fluid.io.batch).

A *reader creator* is a zero-arg callable returning a generator of
samples. Decorators wrap creators into new creators. The implementation is
plain Python (host-side pipeline feeding the device), with threads for the
buffered/xmap stages — the TPU analogue of the reference's
multiprocess+pipe readers, which exist to keep the accelerator fed; the
heavy lifting on this side lives in the C++ batcher (io.native)."""
from __future__ import annotations

import itertools
import queue
import random as _pyrandom
import threading

import numpy as np

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose", "ComposeNotAligned",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader",
           "batch"]


def cache(reader):
    """Cache the first COMPLETE pass in memory; later passes replay it.
    A partially-consumed pass (early break) is discarded, not cached."""
    cached = []
    done = [False]

    def creator():
        if done[0]:
            yield from cached
            return
        this_pass = []
        for item in reader():
            this_pass.append(item)
            yield item
        cached[:] = this_pass  # only a finished pass becomes the cache
        done[0] = True

    return creator


def map_readers(func, *readers):
    """Zip several readers and map func over the tuples."""
    def creator():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return creator


def shuffle(reader, buf_size):
    """Pool-based shuffle with a bounded buffer."""
    def creator():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _pyrandom.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _pyrandom.shuffle(buf)
            yield from buf

    return creator


def chain(*readers):
    """Concatenate readers end to end."""
    def creator():
        for r in readers:
            yield from r()

    return creator


class ComposeNotAligned(ValueError):
    """reference reader/decorator.py:ComposeNotAligned — raised when
    composed readers have different lengths."""


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: (a, b), (c) -> (a, b, c).
    check_alignment=True raises ComposeNotAligned if lengths differ."""
    check_alignment = kwargs.pop("check_alignment", True)

    def _flatten(item):
        if isinstance(item, tuple):
            return item
        return (item,)

    def creator():
        its = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*its):
                if any(i is None for i in items):
                    raise ComposeNotAligned(
                        "compose: readers have different lengths")
                yield sum((_flatten(i) for i in items), ())
        else:
            for items in zip(*its):
                yield sum((_flatten(i) for i in items), ())

    return creator


def buffered(reader, size):
    """Producer thread fills a bounded queue; consumer drains it —
    overlaps host preprocessing with device steps."""
    _end = object()

    def creator():
        q = queue.Queue(maxsize=size)

        def produce():
            try:
                for item in reader():
                    q.put(item)
            finally:
                q.put(_end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _end:
                break
            yield item

    return creator


def firstn(reader, n):
    """Limit to the first n samples."""
    def creator():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return creator


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map with a thread pool (the reference forks processes for
    the GIL; numpy preprocessing releases it, so threads suffice and avoid
    fork+TPU-client hazards)."""
    _end = object()

    class _Raised:
        def __init__(self, exc):
            self.exc = exc

    def creator():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            # sentinel delivery is unconditional so a raising reader can't
            # deadlock the consumer; the error is forwarded and re-raised
            try:
                for i, item in enumerate(reader()):
                    in_q.put((i, item))
            except Exception as e:  # noqa: BLE001
                out_q.put(_Raised(e))
            finally:
                for _ in range(process_num):
                    in_q.put(_end)

        def work():
            try:
                while True:
                    got = in_q.get()
                    if got is _end:
                        break
                    i, item = got
                    out_q.put((i, mapper(item)))
            except Exception as e:  # noqa: BLE001
                out_q.put(_Raised(e))
            finally:
                out_q.put(_end)

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if not order:
            while finished < process_num:
                got = out_q.get()
                if got is _end:
                    finished += 1
                    continue
                if isinstance(got, _Raised):
                    raise got.exc
                yield got[1]
        else:
            pending = {}
            nxt = 0
            while finished < process_num or pending:
                if nxt in pending:
                    yield pending.pop(nxt)
                    nxt += 1
                    continue
                if finished == process_num:
                    break  # workers gone but a hole remains (item dropped)
                got = out_q.get()
                if got is _end:
                    finished += 1
                    continue
                if isinstance(got, _Raised):
                    raise got.exc
                i, item = got
                if i == nxt:
                    yield item
                    nxt += 1
                else:
                    pending[i] = item

    return creator


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run several readers concurrently, interleaving their output
    (thread-backed; see xmap_readers note)."""
    _end = object()

    def creator():
        q = queue.Queue(queue_size)

        def run(r):
            try:
                for item in r():
                    q.put(item)
            finally:
                q.put(_end)

        for r in readers:
            threading.Thread(target=run, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            item = q.get()
            if item is _end:
                finished += 1
                continue
            yield item

    return creator


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (fluid.io.batch /
    paddle.batch parity)."""
    def creator():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return creator
