"""paddle.framework parity package (reference:
python/paddle/framework/__init__.py — random seeding + framework core
re-exports for the 2.0-alpha surface)."""
from .random import seed as manual_seed  # noqa: F401
from .random import get_seed  # noqa: F401
from .tensor import Tensor, Parameter  # noqa: F401
from .device import CPUPlace, CUDAPlace, TPUPlace  # noqa: F401
from .static import (Program, program_guard, default_main_program,  # noqa
                     default_startup_program)
