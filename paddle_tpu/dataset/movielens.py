"""MovieLens-1M style CTR/recommendation data (reference:
python/paddle/dataset/movielens.py — MovieInfo/UserInfo, train/test
readers yielding (user_id, gender, age, job, movie_id, categories,
title_ids, rating)). Synthetic fallback: preference structure =
low-rank user×movie affinity so Wide&Deep/DeepFM models learn signal."""
from __future__ import annotations

import numpy as np

from . import common

NUM_USERS = 800
NUM_MOVIES = 600
NUM_CATEGORIES = 18
TITLE_VOCAB = 1000
MAX_JOB = 21
AGES = [1, 18, 25, 35, 45, 50, 56]
TRAIN_N = 6000
TEST_N = 800


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = index
        self.categories = categories
        self.title = title


class UserInfo:
    def __init__(self, index, gender, age, job):
        self.index = index
        self.is_male = gender == "M"
        self.age = age
        self.job_id = job


def _tables():
    rs = common.rng_for("movielens-tables")
    movies = {}
    for i in range(NUM_MOVIES):
        cats = list(rs.choice(NUM_CATEGORIES,
                              size=int(rs.randint(1, 4)), replace=False))
        title = list(rs.randint(0, TITLE_VOCAB, (int(rs.randint(2, 6)),)))
        movies[i] = MovieInfo(i, cats, title)
    users = {}
    for i in range(NUM_USERS):
        users[i] = UserInfo(i, "M" if rs.rand() < 0.5 else "F",
                            int(rs.choice(AGES)),
                            int(rs.randint(0, MAX_JOB)))
    u = rs.randn(NUM_USERS, 8).astype("f4")
    m = rs.randn(NUM_MOVIES, 8).astype("f4")
    return movies, users, u, m


def movie_info():
    return _tables()[0]


def user_info():
    return _tables()[1]


def max_user_id():
    return NUM_USERS


def max_movie_id():
    return NUM_MOVIES


def max_job_id():
    return MAX_JOB - 1


def age_table():
    return list(AGES)


def categories():
    return [f"cat{i}" for i in range(NUM_CATEGORIES)]


def _samples(n, seed_name):
    movies, users, u, m = _tables()
    rs = common.rng_for(seed_name)
    out = []
    for _ in range(n):
        ui = int(rs.randint(0, NUM_USERS))
        mi = int(rs.randint(0, NUM_MOVIES))
        aff = float(u[ui] @ m[mi]) / 8.0
        rating = int(np.clip(round(3 + aff + rs.randn() * 0.3), 1, 5))
        usr, mov = users[ui], movies[mi]
        age_idx = AGES.index(usr.age)
        out.append((ui, int(usr.is_male), age_idx, usr.job_id, mi,
                    mov.categories, mov.title, float(rating)))
    return out


def train():
    data = _samples(TRAIN_N, "movielens-train")

    def creator():
        yield from data
    return creator


def test():
    data = _samples(TEST_N, "movielens-test")

    def creator():
        yield from data
    return creator


def fetch():
    pass


def get_movie_title_dict():
    """reference movielens.py:get_movie_title_dict — title-word → id."""
    return {f"t{i}": i for i in range(TITLE_VOCAB)}


def movie_categories():
    """reference movielens.py:movie_categories — category → id."""
    return {f"cat{i}": i for i in range(NUM_CATEGORIES)}
