"""paddle_tpu.dataset — the dataset zoo (reference:
python/paddle/dataset/__init__.py). Real files are used when cached under
``common.DATA_HOME``; otherwise deterministic synthetic corpora with the
reference's exact sample formats keep everything runnable offline (see
common.py)."""
from . import common
from . import mnist
from . import cifar
from . import imdb
from . import imikolov
from . import uci_housing
from . import movielens
from . import wmt16
from . import wmt14
from . import conll05
from . import sentiment
from . import voc2012
from . import mq2007
from . import image
from . import flowers

__all__ = ["common", "mnist", "cifar", "imdb", "imikolov", "uci_housing",
           "movielens", "wmt14", "wmt16", "conll05", "sentiment",
           "flowers", "voc2012", "mq2007", "image"]
