"""Movie-review sentiment (reference: python/paddle/dataset/sentiment.py
— NLTK movie_reviews based; readers yield (word ids, 0/1)). Synthetic
fallback shares the IMDB generator with a smaller vocab."""
from __future__ import annotations

from . import common, imdb

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000


def get_word_dict():
    return imdb.word_dict()


def train():
    return imdb.train()


def test():
    return imdb.test()


def fetch():
    pass
