"""IMDB sentiment (reference: python/paddle/dataset/imdb.py — word_dict,
train/test readers yielding ([word ids], 0/1 label)).

Synthetic fallback: a two-regime unigram language — positive and negative
reviews draw from shifted word distributions over a shared vocab — so
bag-of-words / sequence-conv models actually separate the classes."""
from __future__ import annotations

import numpy as np

from . import common

VOCAB = 5000
TRAIN_N = 3000
TEST_N = 500


def word_dict():
    """word -> id (ids 0..VOCAB-1; the reference appends <unk> last)."""
    return {f"w{i}": i for i in range(VOCAB)}


def _samples(n, seed_name):
    rs = common.rng_for(seed_name)
    # two smooth unigram distributions whose mass is shifted apart
    ranks = np.arange(1, VOCAB + 1, dtype="f8")
    base = 1.0 / ranks
    pos = base * (1.0 + 0.8 * np.sin(ranks * 0.01))
    neg = base * (1.0 + 0.8 * np.cos(ranks * 0.01))
    pos /= pos.sum()
    neg /= neg.sum()
    out = []
    for _ in range(n):
        label = int(rs.randint(0, 2))
        length = int(rs.randint(20, 120))
        dist = pos if label else neg
        ids = rs.choice(VOCAB, size=length, p=dist).astype("int64")
        out.append((list(ids), label))
    return out


def train(word_idx=None):
    data = _samples(TRAIN_N, "imdb-train")

    def creator():
        yield from data
    return creator


def test(word_idx=None):
    data = _samples(TEST_N, "imdb-test")

    def creator():
        yield from data
    return creator


def fetch():
    pass


def build_dict(pattern=None, cutoff=0):
    """reference imdb.py:build_dict — frequency-sorted word dict with a
    cutoff; over the synthetic corpus this equals word_dict() (every
    token appears well above any small cutoff)."""
    return word_dict()
