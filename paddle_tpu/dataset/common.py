"""paddle_tpu.dataset.common — dataset cache + offline fallback.

TPU-native rebuild of reference python/paddle/dataset/common.py (DATA_HOME,
download-with-md5 cache, reader conversion helpers).

Offline policy: the reference downloads from public mirrors at import
time. This environment may have zero egress, so every dataset module
first looks for real files under ``DATA_HOME`` (drop the reference's
files there and they are used as-is) and otherwise *generates a
deterministic synthetic corpus with the exact sample format* of the real
dataset (shapes, dtypes, vocab semantics, label ranges). That keeps every
pipeline, model config, and test runnable end-to-end offline; swapping in
the real files changes the numbers, not the code."""
from __future__ import annotations

import hashlib
import os

import numpy as np

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def data_path(*parts):
    return os.path.join(DATA_HOME, *parts)


def has_real(*parts):
    return os.path.exists(data_path(*parts))


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Reference-compatible signature. Returns the cached path if present;
    raises with a clear offline message otherwise (no egress here)."""
    fname = data_path(module_name,
                      save_name or url.split("/")[-1])
    if os.path.exists(fname) and (md5sum is None or
                                  md5file(fname) == md5sum):
        return fname
    raise RuntimeError(
        f"dataset file {fname} not cached and this environment has no "
        f"network egress; place the file there manually (source: {url}) "
        f"or use the synthetic fallback readers")


def rng_for(name):
    """Deterministic per-dataset generator for synthetic fallbacks."""
    seed = int.from_bytes(hashlib.sha256(name.encode()).digest()[:4],
                          "little")
    return np.random.RandomState(seed)


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """reference common.py:split — dump a reader's samples into
    line_count-sized pickle files; returns the file list."""
    import pickle
    dumper = dumper or pickle.dump
    indx_f = 0
    files = []
    lines = []
    for d in reader():
        lines.append(d)
        if len(lines) == line_count:
            filename = suffix % indx_f
            with open(filename, "wb") as f:
                dumper(lines, f)
            files.append(filename)
            indx_f += 1
            lines = []
    if lines:
        filename = suffix % indx_f
        with open(filename, "wb") as f:
            dumper(lines, f)
        files.append(filename)
    return files


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """reference common.py:cluster_files_reader — each trainer reads
    its modulo-slice of the sorted file list."""
    import glob
    import pickle
    loader = loader or pickle.load

    def reader():
        if not callable(loader):
            raise TypeError("loader should be callable.")
        file_list = sorted(glob.glob(files_pattern))
        my_files = [fn for idx, fn in enumerate(file_list)
                    if idx % trainer_count == trainer_id]
        for fn in my_files:
            with open(fn, "rb") as f:
                for line in loader(f):
                    yield line

    return reader
