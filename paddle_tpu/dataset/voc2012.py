"""VOC2012 segmentation (reference: python/paddle/dataset/voc2012.py —
train/val/test readers yielding (image CHW uint8→float, label HW uint8
class mask) with 21 classes).

Synthetic fallback (common.py offline policy): deterministic images of
colored rectangles whose pixel-exact masks are the labels — the same
(image, mask) contract, learnable by a small segmentation net."""
from __future__ import annotations

import os

import numpy as np

from . import common

CLASSES = 21  # 20 object classes + background
H = W = 64
TRAIN_N, VAL_N, TEST_N = 200, 40, 40


def _sample(rs):
    img = np.zeros((3, H, W), "f4")
    mask = np.zeros((H, W), "u1")
    img += rs.rand(3, 1, 1) * 0.1  # background tint
    for _ in range(int(rs.randint(1, 4))):
        cls = int(rs.randint(1, CLASSES))
        y0, x0 = rs.randint(0, H - 16), rs.randint(0, W - 16)
        h, w = rs.randint(8, 24), rs.randint(8, 24)
        y1, x1 = min(y0 + h, H), min(x0 + w, W)
        color = common.rng_for(f"voc-cls-{cls}").rand(3)
        img[:, y0:y1, x0:x1] = color[:, None, None] + \
            0.05 * rs.randn(3, y1 - y0, x1 - x0)
        mask[y0:y1, x0:x1] = cls
    return img.astype("f4"), mask


def _reader(n, seed_name):
    def creator():
        rs = common.rng_for(seed_name)
        for _ in range(n):
            yield _sample(rs)
    return creator


def train():
    """reference: voc2012.py:train."""
    return _reader(TRAIN_N, "voc-train")


def val():
    return _reader(VAL_N, "voc-val")


def test():
    return _reader(TEST_N, "voc-test")


def fetch():
    pass
