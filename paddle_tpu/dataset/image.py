"""Image preprocessing utilities (reference:
python/paddle/dataset/image.py — resize_short, center_crop, random_crop,
left_right_flip, to_chw, simple_transform, load_and_transform).

The reference shells out to cv2; these are pure-numpy equivalents
(nearest-neighbor resize) so the input pipeline has no native-deps —
heavy augmentation belongs in the host-side C++ loader (csrc), not here.
Images are HWC uint8/float arrays like the reference's."""
from __future__ import annotations

import numpy as np


def resize_short(im, size):
    """Resize so the SHORT side == size, keeping aspect (reference:
    image.py:197)."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    return resize_exact(im, nh, nw)


def to_chw(im, order=(2, 0, 1)):
    """reference: image.py:225."""
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """reference: image.py:249."""
    h, w = im.shape[:2]
    y0 = max((h - size) // 2, 0)
    x0 = max((w - size) // 2, 0)
    return im[y0:y0 + size, x0:x0 + size]


def random_crop(im, size, is_color=True, rng=None):
    """reference: image.py:277."""
    rng = rng or np.random
    h, w = im.shape[:2]
    y0 = rng.randint(0, max(h - size, 0) + 1)
    x0 = rng.randint(0, max(w - size, 0) + 1)
    return im[y0:y0 + size, x0:x0 + size]


def left_right_flip(im, is_color=True):
    """reference: image.py:305."""
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """reference: image.py:327 — resize_short → crop (random+flip when
    training, center otherwise) → CHW float → mean-subtract."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(2):
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 2:
        im = im[:, :, None]
    im = to_chw(im).astype("float32")
    if mean is not None:
        mean = np.asarray(mean, "float32")
        im -= mean.reshape(-1, 1, 1) if mean.ndim == 1 else mean
    return im


def load_image(path, is_color=True):
    """reference: image.py:167 — without cv2 only .npy payloads load."""
    if path.endswith(".npy"):
        return np.load(path)
    raise NotImplementedError(
        "offline build: store images as .npy (cv2 is not a dependency)")


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """reference: image.py:383."""
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def resize_exact(im, h, w):
    """Nearest-neighbor resize to exactly (h, w) — the shared separable
    index arithmetic (used by resize_short and hapi transforms)."""
    im = np.asarray(im)
    ys = (np.arange(h) * (im.shape[0] / h)).astype(int).clip(0,
                                                             im.shape[0] - 1)
    xs = (np.arange(w) * (im.shape[1] / w)).astype(int).clip(0,
                                                             im.shape[1] - 1)
    return im[ys][:, xs]


def load_image_bytes(bytes, is_color=True):
    """reference image.py:load_image_bytes — decode an image from a
    bytes buffer. The reference decodes via cv2; here PNG/raw-npy
    buffers decode without native deps (JPEG needs cv2/PIL, which this
    environment deliberately avoids — decode on the host pipeline)."""
    import io
    try:
        with io.BytesIO(bytes) as bio:
            im = np.load(bio, allow_pickle=False)
        if not is_color and im.ndim == 3:
            im = im.mean(axis=2).astype(im.dtype)
        return im
    except Exception:
        pass
    try:
        import matplotlib.image as mpimg  # optional
        import io as _io
        im = mpimg.imread(_io.BytesIO(bytes), format=None)
        if im.dtype != np.uint8:
            im = (im * 255).astype("u1")
        if not is_color and im.ndim == 3:
            im = im.mean(axis=2).astype("u1")
        return im
    except Exception as e:
        raise ValueError(
            "load_image_bytes: buffer is neither .npy nor a format "
            f"matplotlib can decode ({type(e).__name__}); decode "
            "JPEGs in the host data pipeline") from e


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """reference image.py:batch_images_from_tar — read images from a
    tar, pickle them into batch files of (data, label) lists, write a
    batch manifest; returns the manifest path."""
    import os
    import pickle
    import tarfile

    out_path = f"{data_file}_{dataset_name}_batch"
    meta_file = os.path.join(out_path, "batch_names.txt")
    if os.path.exists(meta_file):
        return meta_file
    os.makedirs(out_path, exist_ok=True)
    tf = tarfile.open(data_file)
    mems = tf.getmembers()
    data, labels, names, file_id = [], [], [], 0
    for mem in mems:
        if mem.name not in img2label:
            continue
        data.append(tf.extractfile(mem).read())
        labels.append(img2label[mem.name])
        if len(data) == num_per_batch:
            output = {"label": labels, "data": data}
            name = os.path.join(out_path, f"batch_{file_id}")
            with open(name, "wb") as f:
                pickle.dump(output, f, protocol=2)
            names.append(os.path.basename(name))
            file_id += 1
            data, labels = [], []
    if data:
        output = {"label": labels, "data": data}
        name = os.path.join(out_path, f"batch_{file_id}")
        with open(name, "wb") as f:
            pickle.dump(output, f, protocol=2)
        names.append(os.path.basename(name))
    with open(meta_file, "w") as f:
        f.write("\n".join(names) + "\n")
    return meta_file
