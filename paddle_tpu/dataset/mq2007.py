"""MQ2007 learning-to-rank (reference: python/paddle/dataset/mq2007.py —
LETOR 46-feature query-document data; readers in 'pointwise' (feature,
relevance), 'pairwise' ((better, worse) feature pairs) and 'listwise'
(label list, feature list per query) formats).

Real files: drop MQ2007 train.txt/test.txt under
``DATA_HOME/mq2007/`` (svmlight-ish ``rel qid:n 1:v ... #doc``) and they
are parsed; otherwise a deterministic synthetic corpus with a planted
linear relevance function is generated (common.py offline policy)."""
from __future__ import annotations

import os

import numpy as np

from . import common

FEATURES = 46
QUERIES = {"train": 60, "test": 15}
DOCS_PER_QUERY = 12


def _parse_real(path):
    queries = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = int(float(parts[0]))
            qid = parts[1].split(":")[1]
            feat = np.zeros((FEATURES,), "f4")
            for kv in parts[2:]:
                k, v = kv.split(":")
                idx = int(k) - 1
                if 0 <= idx < FEATURES:
                    feat[idx] = float(v)
            queries.setdefault(qid, []).append((rel, feat))
    return list(queries.values())


def _synthetic(split):
    w = common.rng_for("mq2007-w").randn(FEATURES).astype("f4")
    rs = common.rng_for(f"mq2007-{split}")
    queries = []
    for _ in range(QUERIES[split]):
        docs = []
        for _ in range(DOCS_PER_QUERY):
            feat = rs.rand(FEATURES).astype("f4")
            score = float(feat @ w)
            docs.append((score, feat))
        scores = np.array([s for s, _ in docs])
        # relevance 0..2 by within-query score tertile
        t1, t2 = np.quantile(scores, [0.33, 0.66])
        queries.append([(int(s > t1) + int(s > t2), f) for s, f in docs])
    return queries


def _load(split):
    real = common.data_path("mq2007", f"{split}.txt")
    if os.path.exists(real):
        return _parse_real(real)
    return _synthetic(split)


def _reader(split, format):
    def pointwise():
        for q in _load(split):
            for rel, feat in q:
                yield feat, float(rel)

    def pairwise():
        for q in _load(split):
            for i, (ri, fi) in enumerate(q):
                for rj, fj in q[i + 1:]:
                    if ri > rj:
                        yield fi, fj
                    elif rj > ri:
                        yield fj, fi

    def listwise():
        for q in _load(split):
            labels = [float(rel) for rel, _ in q]
            feats = [f for _, f in q]
            yield labels, feats

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise"):
    """reference: mq2007.py __reader__(train, format)."""
    return _reader("train", format)


def test(format="pairwise"):
    return _reader("test", format)


def fetch():
    pass
