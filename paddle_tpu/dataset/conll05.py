"""CoNLL-2005 SRL-style sequence labeling (reference:
python/paddle/dataset/conll05.py — word/predicate/label dicts + test
reader yielding word ids, context features, predicate, and BIO label
sequence). Synthetic fallback: label sequences generated from a hidden
Markov chain conditioned on word ids — learnable by the CRF/sequence
stack."""
from __future__ import annotations

import numpy as np

from . import common

WORD_VOCAB = 4000
PRED_VOCAB = 300
NUM_LABELS = 19  # BIO over 9 roles + O
TEST_N = 500


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_VOCAB)}
    verb_dict = {f"v{i}": i for i in range(PRED_VOCAB)}
    label_dict = {f"L{i}": i for i in range(NUM_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rs = common.rng_for("conll05-emb")
    return rs.randn(WORD_VOCAB, 32).astype("f4")


def _samples(n, seed_name):
    rs = common.rng_for(seed_name)
    # hidden transition structure for labels + word->label affinity
    trans = rs.dirichlet(np.ones(NUM_LABELS) * 0.3, size=NUM_LABELS)
    emit_affinity = rs.randint(0, NUM_LABELS, (WORD_VOCAB,))
    out = []
    for _ in range(n):
        length = int(rs.randint(5, 30))
        words = rs.randint(0, WORD_VOCAB, (length,)).astype("int64")
        pred = int(rs.randint(0, PRED_VOCAB))
        labels = np.zeros(length, "int64")
        state = int(rs.randint(0, NUM_LABELS))
        for i, w in enumerate(words):
            if rs.rand() < 0.5:
                state = int(emit_affinity[w])
            else:
                state = int(rs.choice(NUM_LABELS, p=trans[state]))
            labels[i] = state
        # reference yields 8 context slices + predicate + mark + labels;
        # we keep (words, predicate, labels) — the learnable core
        out.append((list(words), pred, list(labels)))
    return out


def test():
    data = _samples(TEST_N, "conll05-test")

    def creator():
        yield from data
    return creator


def fetch():
    pass
