"""PTB-style n-gram LM data (reference: python/paddle/dataset/imikolov.py —
word_dict via build_dict, train/test readers yielding n-gram tuples or
seq data). Synthetic fallback: a Markov-chain corpus over a Zipf vocab so
word2vec/NGram models have real bigram structure to learn."""
from __future__ import annotations

import numpy as np

from . import common


class DataType:
    NGRAM = 1
    SEQ = 2


VOCAB = 2000
TRAIN_SENTS = 2000
TEST_SENTS = 200


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(VOCAB)}


word_dict = build_dict


def _sentences(n, seed_name):
    rs = common.rng_for(seed_name)
    # sparse random Markov transitions give learnable bigram stats;
    # the chain is split-independent so train and test share statistics
    next_words = common.rng_for("imikolov-chain").randint(
        0, VOCAB, (VOCAB, 5)).astype("int64")
    out = []
    for _ in range(n):
        length = int(rs.randint(5, 25))
        w = int(rs.randint(0, VOCAB))
        sent = [w]
        for _ in range(length - 1):
            w = int(next_words[w, rs.randint(0, 5)])
            sent.append(w)
        out.append(sent)
    return out


def _reader(sents, word_idx, n, data_type):
    def creator():
        for sent in sents:
            if data_type == DataType.NGRAM:
                if len(sent) < n:
                    continue
                for i in range(n - 1, len(sent)):
                    yield tuple(sent[i - n + 1:i + 1])
            else:
                yield sent[:-1], sent[1:]
    return creator


def train(word_idx=None, n=5, data_type=DataType.NGRAM):
    return _reader(_sentences(TRAIN_SENTS, "imikolov-train"), word_idx, n,
                   data_type)


def test(word_idx=None, n=5, data_type=DataType.NGRAM):
    return _reader(_sentences(TEST_SENTS, "imikolov-test"), word_idx, n,
                   data_type)


def fetch():
    pass
