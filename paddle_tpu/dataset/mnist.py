"""MNIST (reference: python/paddle/dataset/mnist.py — idx-format parser,
train:91/test:108 readers yielding (image[784] float32 in [-1,1], label)).

Real idx files under DATA_HOME/mnist are parsed; otherwise a synthetic
set of blurred class-template digits (same format, 10 classes) is
generated deterministically so LeNet-style configs actually converge."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

TRAIN_N = 8000   # synthetic sizes (real idx files override)
TEST_N = 1000


def _parse_idx(image_path, label_path):
    with gzip.open(image_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
    with gzip.open(label_path, "rb") as f:
        struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8)
    images = images.astype("float32") / 127.5 - 1.0
    return images, labels.astype("int64")


def _synthetic(n, seed_name):
    rs = common.rng_for(seed_name)
    # class templates: 10 fixed random blobs, low-pass filtered; samples
    # are jittered templates -> linearly separable enough to learn.
    # Templates come from a split-independent seed so train and test
    # draw from the SAME class distributions.
    templates = common.rng_for("mnist-templates").randn(
        10, 28, 28).astype("f4")
    k = np.ones((5, 5), "f4") / 25.0
    from numpy.lib.stride_tricks import sliding_window_view
    smoothed = []
    for t in templates:
        p = np.pad(t, 2, mode="edge")
        smoothed.append(
            sliding_window_view(p, (5, 5)).reshape(28, 28, 25) @ k.ravel())
    templates = np.stack(smoothed) * 3.0
    labels = rs.randint(0, 10, (n,)).astype("int64")
    noise = rs.randn(n, 28, 28).astype("f4") * 0.35
    images = np.tanh(templates[labels] + noise).reshape(n, 784)
    return images.astype("f4"), labels


def _reader(images, labels):
    def creator():
        for img, lab in zip(images, labels):
            yield img, int(lab)
    return creator


def _load(split):
    img_f = common.data_path("mnist", f"{split}-images-idx3-ubyte.gz")
    lab_f = common.data_path("mnist", f"{split}-labels-idx1-ubyte.gz")
    if os.path.exists(img_f) and os.path.exists(lab_f):
        return _parse_idx(img_f, lab_f)
    n = TRAIN_N if split == "train" else TEST_N
    return _synthetic(n, f"mnist-{split}")


def train():
    """Reader creator: yields (image [784] float32 in [-1,1], label int)."""
    return _reader(*_load("train"))


def test():
    return _reader(*_load("t10k" if common.has_real(
        "mnist", "t10k-images-idx3-ubyte.gz") else "test"))


def train_arrays():
    """Whole split as arrays (fast path for the native batcher)."""
    return _load("train")


def test_arrays():
    return _load("t10k" if common.has_real(
        "mnist", "t10k-images-idx3-ubyte.gz") else "test")


def fetch():
    _load("train")
