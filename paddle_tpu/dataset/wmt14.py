"""WMT14 en-fr translation pairs (reference:
python/paddle/dataset/wmt14.py — train/test readers yielding
(src_ids, trg_ids, trg_next_ids); dict_size-truncated vocabs with
<s>=0, <e>=1, <unk>=2).

Synthetic fallback (common.py offline policy): the same deterministic
cipher-translation construction as wmt16 but with wmt14's reader
signature (train(dict_size)/test(dict_size)) and vocab conventions."""
from __future__ import annotations

import numpy as np

from . import common

BOS, EOS, UNK = 0, 1, 2
TRAIN_N = 3000
TEST_N = 300
_DEFAULT_DICT = 30000


def _perm(dict_size):
    rs = common.rng_for("wmt14-perm")
    perm = np.arange(3, dict_size)
    rs.shuffle(perm)
    return perm


def _samples(n, seed_name, dict_size):
    rs = common.rng_for(seed_name)
    perm = _perm(dict_size)
    out = []
    for _ in range(n):
        length = int(rs.randint(4, 24))
        src = rs.randint(3, dict_size, (length,)).astype("int64")
        trg = perm[src - 3]
        trg_in = np.concatenate([[BOS], trg]).astype("int64")
        trg_next = np.concatenate([trg, [EOS]]).astype("int64")
        out.append((list(src), list(trg_in), list(trg_next)))
    return out


def _reader(n, seed_name, dict_size):
    def creator():
        for s in _samples(n, seed_name, dict_size):
            yield s
    return creator


def train(dict_size=_DEFAULT_DICT):
    return _reader(TRAIN_N, "wmt14-train", dict_size)


def test(dict_size=_DEFAULT_DICT):
    return _reader(TEST_N, "wmt14-test", dict_size)


def get_dict(dict_size, reverse=True):
    """reference: wmt14.py:get_dict — (src_dict, trg_dict); reverse=True
    maps id→word (the reference default)."""
    src = {"<s>": BOS, "<e>": EOS, "<unk>": UNK}
    for i in range(3, dict_size):
        src[f"w{i}"] = i
    trg = dict(src)
    if reverse:
        return ({v: k for k, v in src.items()},
                {v: k for k, v in trg.items()})
    return src, trg


def fetch():
    pass
