"""WMT16-style translation pairs (reference:
python/paddle/dataset/wmt16.py — get_dict, train/test readers yielding
(src_ids, trg_ids, trg_next_ids) with <s>/<e>/<unk> conventions).

Synthetic fallback: a deterministic "cipher translation" task — target =
per-token bijective mapping of source with local reorderings — a real
learnable seq2seq task with the reference's token conventions."""
from __future__ import annotations

import numpy as np

from . import common

SRC_VOCAB = 3000
TRG_VOCAB = 3000
BOS, EOS, UNK = 0, 1, 2
TRAIN_N = 3000
TEST_N = 300


def get_dict(lang, dict_size=None, reverse=False):
    size = SRC_VOCAB if lang in ("en", "src") else TRG_VOCAB
    d = {"<s>": BOS, "<e>": EOS, "<unk>": UNK}
    for i in range(3, size):
        d[f"{lang}{i}"] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _permutation():
    rs = common.rng_for("wmt16-perm")
    perm = np.arange(3, TRG_VOCAB)
    rs.shuffle(perm)
    return perm  # src token i+3 -> trg token perm[i]


def _samples(n, seed_name):
    rs = common.rng_for(seed_name)
    perm = _permutation()
    out = []
    for _ in range(n):
        length = int(rs.randint(4, 20))
        src = rs.randint(3, SRC_VOCAB, (length,)).astype("int64")
        trg = perm[src - 3]
        # local swap noise: adjacent pairs swapped with p=0.2
        for i in range(0, length - 1, 2):
            if rs.rand() < 0.2:
                trg[i], trg[i + 1] = trg[i + 1], trg[i]
        src_ids = list(src)
        trg_in = [BOS] + list(trg)
        trg_next = list(trg) + [EOS]
        out.append((src_ids, trg_in, trg_next))
    return out


def train(src_dict_size=SRC_VOCAB, trg_dict_size=TRG_VOCAB,
          src_lang="en"):
    data = _samples(TRAIN_N, "wmt16-train")

    def creator():
        yield from data
    return creator


def test(src_dict_size=SRC_VOCAB, trg_dict_size=TRG_VOCAB, src_lang="en"):
    data = _samples(TEST_N, "wmt16-test")

    def creator():
        yield from data
    return creator


def validation(src_dict_size=SRC_VOCAB, trg_dict_size=TRG_VOCAB,
               src_lang="en"):
    data = _samples(TEST_N, "wmt16-val")

    def creator():
        yield from data
    return creator


def fetch():
    pass
