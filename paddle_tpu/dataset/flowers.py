"""Oxford-102 flowers (reference: python/paddle/dataset/flowers.py —
images + segmentation labels, 102 classes). Synthetic fallback: small
class-structured RGB images in the reference's (chw float32, label)
format (sized for model smoke tests rather than 224² realism)."""
from __future__ import annotations

import numpy as np

from . import common

NUM_CLASSES = 102
TRAIN_N = 1020
TEST_N = 204
SIZE = 32  # synthetic images are 3xSIZExSIZE


def _samples(n, seed_name):
    rs = common.rng_for(seed_name)
    trs = common.rng_for("flowers-templates")  # shared across splits
    base = trs.rand(NUM_CLASSES, 3, 1, 1).astype("f4")
    pattern = trs.rand(NUM_CLASSES, 3, SIZE, SIZE).astype("f4") * 0.4
    labels = rs.randint(0, NUM_CLASSES, (n,)).astype("int64")
    noise = rs.rand(n, 3, SIZE, SIZE).astype("f4") * 0.2
    imgs = np.clip(base[labels] * 0.5 + pattern[labels] + noise, 0, 1)
    return imgs.astype("f4"), labels


def _reader(images, labels):
    def creator():
        for img, lab in zip(images, labels):
            yield img, int(lab)
    return creator


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(*_samples(TRAIN_N, "flowers-train"))


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(*_samples(TEST_N, "flowers-test"))


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader(*_samples(TEST_N, "flowers-valid"))


def fetch():
    pass
