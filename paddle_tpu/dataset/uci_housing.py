"""UCI housing regression (reference: python/paddle/dataset/uci_housing.py
— 13 normalized features, price target). Synthetic fallback: a fixed
linear ground truth + noise in the same normalized feature space."""
from __future__ import annotations

import os

import numpy as np

from . import common

FEATURES = 13
TRAIN_N = 400
TEST_N = 100


def _load(split):
    f = common.data_path("uci_housing", "housing.data")
    if os.path.exists(f):
        raw = np.loadtxt(f).astype("f4")
        x = raw[:, :-1]
        y = raw[:, -1:]
        x = (x - x.mean(0)) / (x.std(0) + 1e-6)
        cut = int(len(x) * 0.8)
        return (x[:cut], y[:cut]) if split == "train" else (x[cut:], y[cut:])
    rs = common.rng_for(f"uci-{split}")
    n = TRAIN_N if split == "train" else TEST_N
    w = common.rng_for("uci-w").randn(FEATURES, 1).astype("f4")
    x = rs.randn(n, FEATURES).astype("f4")
    y = x @ w + 0.1 * rs.randn(n, 1).astype("f4") + 22.5
    return x, y.astype("f4")


def _reader(x, y):
    def creator():
        for xi, yi in zip(x, y):
            yield xi, yi
    return creator


def train():
    return _reader(*_load("train"))


def test():
    return _reader(*_load("test"))


def train_arrays():
    return _load("train")


def fetch():
    pass
