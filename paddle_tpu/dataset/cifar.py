"""CIFAR-10/100 (reference: python/paddle/dataset/cifar.py — pickled
batches yielding (image[3072] float32 in [0,1], label)).

Real python-pickle tarballs under DATA_HOME/cifar are used when present;
otherwise synthetic class-colored images (same 3×32×32 flat format)."""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from . import common

TRAIN_N = 4000
TEST_N = 800


def _synthetic(n, num_classes, seed_name):
    rs = common.rng_for(seed_name)
    # class templates from a split-independent seed (train and test must
    # share class distributions)
    trs = common.rng_for(f"cifar{num_classes}-templates")
    base = trs.rand(num_classes, 3, 1, 1).astype("f4")
    pattern = trs.rand(num_classes, 3, 32, 32).astype("f4") * 0.3
    labels = rs.randint(0, num_classes, (n,)).astype("int64")
    noise = rs.rand(n, 3, 32, 32).astype("f4") * 0.25
    imgs = np.clip(base[labels] * 0.6 + pattern[labels] + noise, 0, 1)
    return imgs.reshape(n, 3072).astype("f4"), labels


def _from_tar(path, key_prefix, num_classes):
    images, labels = [], []
    with tarfile.open(path) as tf:
        for m in tf.getmembers():
            if key_prefix in m.name and m.isfile():
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                images.append(np.asarray(d[b"data"], "f4") / 255.0)
                labs = d.get(b"labels", d.get(b"fine_labels"))
                labels.append(np.asarray(labs, "int64"))
    return np.concatenate(images), np.concatenate(labels)


def _load(num_classes, split):
    tar = common.data_path(
        "cifar", f"cifar-{num_classes}-python.tar.gz")
    if os.path.exists(tar):
        prefix = "test" if split == "test" else ("data_batch"
                                                 if num_classes == 10
                                                 else "train")
        return _from_tar(tar, prefix, num_classes)
    n = TRAIN_N if split == "train" else TEST_N
    return _synthetic(n, num_classes, f"cifar{num_classes}-{split}")


def _reader(images, labels):
    def creator():
        for img, lab in zip(images, labels):
            yield img, int(lab)
    return creator


def train10():
    return _reader(*_load(10, "train"))


def test10():
    return _reader(*_load(10, "test"))


def train100():
    return _reader(*_load(100, "train"))


def test100():
    return _reader(*_load(100, "test"))


def train_arrays(num_classes=10):
    return _load(num_classes, "train")


def fetch():
    _load(10, "train")
