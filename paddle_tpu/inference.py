"""paddle_tpu.inference — the inference engine.

TPU-native rebuild of the reference's inference stack
(reference: paddle/fluid/inference/api/analysis_predictor.cc +
paddle_inference_api.h; TensorRT subgraph pass). On TPU the optimizing
compiler IS XLA: a Predictor functionalizes the saved Layer and AOT-
compiles `jit(...).lower().compile()` per input signature — the analogue
of the reference's analysis passes + engine build, with bf16 as the
TensorRT-precision analogue.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor
from .nn.layer import Layer, functional_call, state_pytree


class Config:
    """reference: AnalysisConfig — precision / model path knobs."""

    def __init__(self, model_path=None):
        self.model_path = model_path
        self.precision = "float32"   # or "bfloat16"
        self.donate_inputs = False

    def enable_bf16(self):
        self.precision = "bfloat16"
        return self

    def enable_int8(self, calibration_data=None):
        """int8 post-training quantization (the reference's TensorRT-int8
        analogue): Linear/Conv2D weights stored int8, dequantized into
        the matmul; `calibration_data` (iterable of input batches)
        additionally calibrates activation scales."""
        self.precision = "int8"
        self.calibration_data = calibration_data
        return self


class Predictor:
    """reference: AnalysisPredictor. Wraps an eval-mode Layer; each input
    signature is lowered + compiled once (AOT) and cached."""

    def __init__(self, model_or_config, config=None):
        if isinstance(model_or_config, Config):
            config = model_or_config
            from . import io as pio
            model = pio.load_inference_model(config.model_path)
        else:
            model = model_or_config
        self.config = config or Config()
        if self.config.precision == "int8":
            from .quantization import convert, quant_post_static
            cal = getattr(self.config, "calibration_data", None)
            if cal is not None:
                model = quant_post_static(model, cal)
            else:
                model = convert(model)
        self.model = model.eval()
        self.state = state_pytree(model)
        if self.config.precision == "bfloat16":
            self.state = {k: (v.astype(jnp.bfloat16)
                              if jnp.issubdtype(v.dtype, jnp.floating)
                              else v)
                          for k, v in self.state.items()}
        self._compiled = {}

    def _signature(self, args):
        return tuple((a.shape, str(a.dtype)) for a in args)

    def run(self, *inputs):
        """Run inference; inputs are numpy arrays / Tensors. Returns
        numpy outputs (list when the model returns several)."""
        arrays = []
        for x in inputs:
            if isinstance(x, Tensor):
                x = x.data
            arrays.append(jnp.asarray(x))
        key = self._signature(arrays)
        if key not in self._compiled:
            self._compiled[key] = self._build(arrays)
        out = self._compiled[key](self.state, *arrays)
        if isinstance(out, (tuple, list)):
            return [np.asarray(jax.device_get(o)) for o in out]
        return np.asarray(jax.device_get(out))

    def _build(self, arrays):
        model = self.model

        def fn(state, *xs):
            from . import autograd as _ag
            with _ag.no_grad():
                out, _ = functional_call(model, state,
                                         *[Tensor(x) for x in xs])
            flat, tree = jax.tree_util.tree_flatten(
                out, is_leaf=lambda t: isinstance(t, Tensor))
            arr = [t.data if isinstance(t, Tensor) else t for t in flat]
            return tuple(arr) if len(arr) > 1 else arr[0]

        # AOT: lower + compile now, not on first call
        lowered = jax.jit(fn).lower(self.state, *arrays)
        return lowered.compile()

    def compile_report(self, *inputs):
        """Expose the compiled executable's cost analysis (profiling aid)."""
        arrays = [jnp.asarray(x.data if isinstance(x, Tensor) else x)
                  for x in inputs]
        key = self._signature(arrays)
        if key not in self._compiled:
            self._compiled[key] = self._build(arrays)
        exe = self._compiled[key]
        try:
            return exe.cost_analysis()
        except Exception:
            return {}


def create_predictor(config):
    """reference: paddle_infer.create_predictor."""
    return Predictor(config)
