"""paddle_tpu.inference — the inference engine.

TPU-native rebuild of the reference's inference stack
(reference: paddle/fluid/inference/api/analysis_predictor.cc +
paddle_inference_api.h; TensorRT subgraph pass). On TPU the optimizing
compiler IS XLA: a Predictor functionalizes the saved Layer and AOT-
compiles `jit(...).lower().compile()` per input signature — the analogue
of the reference's analysis passes + engine build, with bf16 as the
TensorRT-precision analogue.
"""
from __future__ import annotations

import threading
import warnings

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor
from .nn.layer import Layer, functional_call, state_pytree

_monitor = None
_COST_WARNED = False

# Tracing binds the state pytree into the (possibly shared) Layer IN
# PLACE (nn.layer.bind_state), so two concurrent traces would read each
# other's tracers and compile executables with phantom inputs. One
# process-wide lock serializes compilation — serving makes concurrent
# first-compiles an everyday event (N client threads + the batcher
# drain thread), and steady state never takes this path.
_BUILD_LOCK = threading.Lock()


def _mon():
    # lazy: paddle_tpu/__init__ imports inference before monitor
    global _monitor
    if _monitor is None:
        from . import monitor
        _monitor = monitor
    return _monitor


def _infer_fn(model, state=None):
    """The one functionalized, no-grad inference body both compile paths
    share. With ``state=None`` the returned fn takes ``(state, *xs)`` —
    the jit path, where weights stay arguments so one executable serves
    updated states; with a concrete ``state`` it is closed over — the
    export path, where weights bake into the artifact as constants."""

    def call(st, xs):
        from . import autograd as _ag
        with _ag.no_grad():
            out, _ = functional_call(model, st, *[Tensor(x) for x in xs])
        flat, _tree = jax.tree_util.tree_flatten(
            out, is_leaf=lambda t: isinstance(t, Tensor))
        arr = [t.data if isinstance(t, Tensor) else t for t in flat]
        return tuple(arr) if len(arr) > 1 else arr[0]

    if state is None:
        def fn(st, *xs):
            return call(st, xs)
    else:
        def fn(*xs):
            return call(state, xs)
    return fn


class Config:
    """reference: AnalysisConfig — precision / model path knobs."""

    def __init__(self, model_path=None):
        self.model_path = model_path
        self.precision = "float32"   # or "bfloat16"
        self.donate_inputs = False

    def enable_bf16(self):
        self.precision = "bfloat16"
        return self

    def enable_int8(self, calibration_data=None):
        """int8 post-training quantization (the reference's TensorRT-int8
        analogue). With `calibration_data` (iterable of input batches)
        activation scales are calibrated and Linear/Conv2D run REAL
        int8 x int8 -> int32 MXU math (lax.dot_general/conv with int32
        accumulation), float only at the edges; without calibration,
        weights ship int8 and dequantize into the matmul (memory win
        only)."""
        self.precision = "int8"
        self.calibration_data = calibration_data
        return self


class Predictor:
    """reference: AnalysisPredictor. Wraps an eval-mode Layer; each input
    signature is lowered + compiled once (AOT) and cached."""

    def __init__(self, model_or_config, config=None):
        caller_owns_model = False
        if isinstance(model_or_config, Config):
            config = model_or_config
            from . import io as pio
            model = pio.load_inference_model(config.model_path)
        else:
            model = model_or_config
            caller_owns_model = True
        self.config = config or Config()
        if self.config.precision == "int8":
            from .quantization import convert, quant_post_static
            if caller_owns_model:
                # quantize a COPY: convert/quant_post_static rewrap
                # layers in place, and the caller's model must stay
                # float (they may build other Predictors from it or keep
                # training it). A path-loaded model is already private —
                # no copy, no doubled peak memory.
                import copy
                model = copy.deepcopy(model)
            cal = getattr(self.config, "calibration_data", None)
            if cal is not None:
                model = quant_post_static(model, cal)
            else:
                model = convert(model)
        self.model = model.eval()
        self.state = state_pytree(model)
        if self.config.precision == "bfloat16":
            self.state = {k: (v.astype(jnp.bfloat16)
                              if jnp.issubdtype(v.dtype, jnp.floating)
                              else v)
                          for k, v in self.state.items()}
        self._compiled = {}
        # telemetry sampler provider: compiled-executable count as a
        # live gauge; weakref so a dead Predictor self-unregisters
        import weakref
        from .monitor import sampler as _sampler
        ref = weakref.ref(self)

        def _exe_series():
            p = ref()
            if p is None:
                return None
            return {"inference.executables": len(p._compiled)}

        _sampler.register_provider(f"predictor-{id(self)}", _exe_series)

    def _signature(self, args):
        return tuple((a.shape, str(a.dtype)) for a in args)

    def run(self, *inputs, buckets=None):
        """Run inference; inputs are numpy arrays / Tensors. Returns
        numpy outputs (list when the model returns several). With
        ``buckets`` (True for powers of two, or an explicit size list)
        the batch dim is padded up to the next bucket before dispatch
        and per-example outputs are sliced back — ragged request sizes
        stop minting fresh executables (see docs/serving.md)."""
        out = self.run_device(*inputs, buckets=buckets)
        if isinstance(out, (tuple, list)):
            return [np.asarray(jax.device_get(o)) for o in out]
        return np.asarray(jax.device_get(out))

    def run_device(self, *inputs, buckets=None):
        """Like run() but returns DEVICE arrays (jax.Array) without the
        device→host copy: for pipelined serving, feeding one predictor's
        output to another, or batched scoring loops where only the final
        result (or a reduction) leaves the device. Inputs may be numpy,
        Tensors, or device arrays — device inputs skip the host→device
        copy too. ``buckets`` as in :meth:`run`."""
        arrays = []
        for x in inputs:
            if isinstance(x, Tensor):
                x = x.data
            arrays.append(jnp.asarray(x))
        real_n = None
        if buckets and arrays and getattr(arrays[0], "ndim", 0) >= 1:
            from .io.bucketing import next_bucket, pad_to_bucket
            bset = None if buckets is True else buckets
            n = arrays[0].shape[0]
            target = next_bucket(n, bset)
            if target != n:
                real_n = n
                arrays = [pad_to_bucket(a, target)
                          if getattr(a, "ndim", 0) >= 1
                          and a.shape[0] == n else a
                          for a in arrays]
                m = _mon()
                if m.enabled():
                    m.counter("inference.bucket_pad").inc()
        out = self._get_compiled(arrays)(self.state, *arrays)
        if real_n is not None:
            from .io.bucketing import unpad
            if isinstance(out, (tuple, list)):
                out = tuple(unpad(o, real_n) for o in out)
            else:
                out = unpad(out, real_n)
        return out

    def _get_compiled(self, arrays):
        """Cache lookup keyed on (shape, dtype) only — numpy, Tensor and
        device-array inputs of one signature share one executable.
        Thread-safe: misses serialize on the build lock (double-checked,
        so a signature another thread just compiled becomes a hit)."""
        key = self._signature(arrays)
        exe = self._compiled.get(key)
        m = _mon()
        if exe is None:
            with _BUILD_LOCK:
                exe = self._compiled.get(key)
                if exe is None:
                    if m.enabled():
                        m.counter("inference.compile").inc()
                    with m.trace.span("inference.compile",
                                      model=type(self.model).__name__):
                        exe = self._compiled[key] = self._build(arrays)
                    return exe
        if m.enabled():
            m.counter("inference.cache_hit").inc()
        return exe

    def _build(self, arrays):
        # AOT: lower + compile now, not on first call (arrays may be
        # concrete values or ShapeDtypeStructs — warmup's path). Callers
        # hold _BUILD_LOCK.
        lowered = jax.jit(_infer_fn(self.model)).lower(self.state, *arrays)
        return lowered.compile()

    def warmup(self, *signatures):
        """AOT-compile ahead of traffic: each signature is a list with
        one ``(shape, dtype)`` pair (or template array) per model input.
        Compiles via ``lower().compile()`` over ShapeDtypeStructs — no
        example data needed, same cache key :meth:`run` computes, so the
        first real request of that shape starts on a warm executable
        (``Executor.warmup``'s discipline, applied to inference).
        Returns the cache keys."""
        keys = []
        for sig in signatures:
            specs = []
            for item in sig:
                if hasattr(item, "shape") and hasattr(item, "dtype"):
                    shape, dtype = item.shape, item.dtype
                else:
                    shape, dtype = item
                dtype = jax.dtypes.canonicalize_dtype(np.dtype(dtype))
                specs.append(jax.ShapeDtypeStruct(
                    tuple(int(s) for s in shape), dtype))
            key = self._signature(specs)
            if key not in self._compiled:
                with _BUILD_LOCK:
                    if key not in self._compiled:
                        m = _mon()
                        if m.enabled():
                            m.counter("inference.aot_warmup").inc()
                        with m.trace.span("inference.warmup",
                                          shape=str(specs[0].shape)):
                            self._compiled[key] = self._build(specs)
            keys.append(key)
        return keys

    def export(self, path, *example_inputs):
        """Serialize the model as a portable StableHLO artifact
        (jax.export) — the TPU-native analogue of the reference's
        save-for-C-API flow (paddle/fluid/inference/capi): any PJRT host
        (C/C++/Go via the PJRT C API, or another Python) can load and run
        it without this framework. Weights are BAKED into the artifact as
        constants (like the reference's frozen inference programs)."""
        from jax import export as jexport

        arrays = [jnp.asarray(x.data if isinstance(x, Tensor) else x)
                  for x in example_inputs]
        fn = _infer_fn(self.model, state=self.state)
        exported = jexport.export(jax.jit(fn))(
            *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays])
        with open(path, "wb") as f:
            f.write(exported.serialize())
        return path

    def compile_report(self, *inputs):
        """The compiled executable's XLA-measured cost (flops, bytes,
        peak memory), extracted through ``monitor.xla`` — the same
        normalization ``aot_capture`` applies everywhere else, so the
        numbers also land in the ``xla.*`` gauges / ``xla_cost`` JSONL
        when the monitor is enabled. Warns once (rather than silently
        returning ``{}``) when the backend exposes no cost analysis."""
        arrays = [jnp.asarray(x.data if isinstance(x, Tensor) else x)
                  for x in inputs]
        exe = self._get_compiled(arrays)
        from .monitor import xla as _xla
        label = f"predictor.{type(self.model).__name__}"
        info = _xla.capture(label, exe)
        if not info:
            global _COST_WARNED
            if not _COST_WARNED:
                _COST_WARNED = True
                warnings.warn(
                    "Predictor.compile_report: this backend exposes no "
                    "cost/memory analysis for compiled executables; "
                    "returning an empty report", RuntimeWarning)
        return info


def load_exported(path):
    """Load a Predictor.export artifact; returns a callable taking numpy
    arrays and returning numpy outputs (runs via jax.export.deserialize —
    no model class needed)."""
    from jax import export as jexport

    with open(path, "rb") as f:
        exported = jexport.deserialize(bytearray(f.read()))

    def run(*inputs):
        arrays = [jnp.asarray(x.data if isinstance(x, Tensor) else x)
                  for x in inputs]
        out = exported.call(*arrays)
        if isinstance(out, (tuple, list)):
            return [np.asarray(jax.device_get(o)) for o in out]
        return np.asarray(jax.device_get(out))

    return run


def create_predictor(config):
    """reference: paddle_infer.create_predictor."""
    return Predictor(config)
