"""paddle_tpu.inference — the inference engine.

TPU-native rebuild of the reference's inference stack
(reference: paddle/fluid/inference/api/analysis_predictor.cc +
paddle_inference_api.h; TensorRT subgraph pass). On TPU the optimizing
compiler IS XLA: a Predictor functionalizes the saved Layer and AOT-
compiles `jit(...).lower().compile()` per input signature — the analogue
of the reference's analysis passes + engine build, with bf16 as the
TensorRT-precision analogue.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor
from .nn.layer import Layer, functional_call, state_pytree


class Config:
    """reference: AnalysisConfig — precision / model path knobs."""

    def __init__(self, model_path=None):
        self.model_path = model_path
        self.precision = "float32"   # or "bfloat16"
        self.donate_inputs = False

    def enable_bf16(self):
        self.precision = "bfloat16"
        return self

    def enable_int8(self, calibration_data=None):
        """int8 post-training quantization (the reference's TensorRT-int8
        analogue). With `calibration_data` (iterable of input batches)
        activation scales are calibrated and Linear/Conv2D run REAL
        int8 x int8 -> int32 MXU math (lax.dot_general/conv with int32
        accumulation), float only at the edges; without calibration,
        weights ship int8 and dequantize into the matmul (memory win
        only)."""
        self.precision = "int8"
        self.calibration_data = calibration_data
        return self


class Predictor:
    """reference: AnalysisPredictor. Wraps an eval-mode Layer; each input
    signature is lowered + compiled once (AOT) and cached."""

    def __init__(self, model_or_config, config=None):
        caller_owns_model = False
        if isinstance(model_or_config, Config):
            config = model_or_config
            from . import io as pio
            model = pio.load_inference_model(config.model_path)
        else:
            model = model_or_config
            caller_owns_model = True
        self.config = config or Config()
        if self.config.precision == "int8":
            from .quantization import convert, quant_post_static
            if caller_owns_model:
                # quantize a COPY: convert/quant_post_static rewrap
                # layers in place, and the caller's model must stay
                # float (they may build other Predictors from it or keep
                # training it). A path-loaded model is already private —
                # no copy, no doubled peak memory.
                import copy
                model = copy.deepcopy(model)
            cal = getattr(self.config, "calibration_data", None)
            if cal is not None:
                model = quant_post_static(model, cal)
            else:
                model = convert(model)
        self.model = model.eval()
        self.state = state_pytree(model)
        if self.config.precision == "bfloat16":
            self.state = {k: (v.astype(jnp.bfloat16)
                              if jnp.issubdtype(v.dtype, jnp.floating)
                              else v)
                          for k, v in self.state.items()}
        self._compiled = {}

    def _signature(self, args):
        return tuple((a.shape, str(a.dtype)) for a in args)

    def run(self, *inputs):
        """Run inference; inputs are numpy arrays / Tensors. Returns
        numpy outputs (list when the model returns several)."""
        out = self.run_device(*inputs)
        if isinstance(out, (tuple, list)):
            return [np.asarray(jax.device_get(o)) for o in out]
        return np.asarray(jax.device_get(out))

    def run_device(self, *inputs):
        """Like run() but returns DEVICE arrays (jax.Array) without the
        device→host copy: for pipelined serving, feeding one predictor's
        output to another, or batched scoring loops where only the final
        result (or a reduction) leaves the device. Inputs may be numpy,
        Tensors, or device arrays — device inputs skip the host→device
        copy too."""
        arrays = []
        for x in inputs:
            if isinstance(x, Tensor):
                x = x.data
            arrays.append(jnp.asarray(x))
        key = self._signature(arrays)
        if key not in self._compiled:
            self._compiled[key] = self._build(arrays)
        return self._compiled[key](self.state, *arrays)

    def _build(self, arrays):
        model = self.model

        def fn(state, *xs):
            from . import autograd as _ag
            with _ag.no_grad():
                out, _ = functional_call(model, state,
                                         *[Tensor(x) for x in xs])
            flat, tree = jax.tree_util.tree_flatten(
                out, is_leaf=lambda t: isinstance(t, Tensor))
            arr = [t.data if isinstance(t, Tensor) else t for t in flat]
            return tuple(arr) if len(arr) > 1 else arr[0]

        # AOT: lower + compile now, not on first call
        lowered = jax.jit(fn).lower(self.state, *arrays)
        return lowered.compile()

    def export(self, path, *example_inputs):
        """Serialize the model as a portable StableHLO artifact
        (jax.export) — the TPU-native analogue of the reference's
        save-for-C-API flow (paddle/fluid/inference/capi): any PJRT host
        (C/C++/Go via the PJRT C API, or another Python) can load and run
        it without this framework. Weights are BAKED into the artifact as
        constants (like the reference's frozen inference programs)."""
        from jax import export as jexport

        arrays = [jnp.asarray(x.data if isinstance(x, Tensor) else x)
                  for x in example_inputs]
        model = self.model
        state = self.state

        def fn(*xs):
            from . import autograd as _ag
            with _ag.no_grad():
                out, _ = functional_call(model, state,
                                         *[Tensor(x) for x in xs])
            flat, _tree = jax.tree_util.tree_flatten(
                out, is_leaf=lambda t: isinstance(t, Tensor))
            arr = [t.data if isinstance(t, Tensor) else t for t in flat]
            return tuple(arr) if len(arr) > 1 else arr[0]

        exported = jexport.export(jax.jit(fn))(
            *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays])
        with open(path, "wb") as f:
            f.write(exported.serialize())
        return path

    def compile_report(self, *inputs):
        """Expose the compiled executable's cost analysis (profiling
        aid)."""
        arrays = [jnp.asarray(x.data if isinstance(x, Tensor) else x)
                  for x in inputs]
        key = self._signature(arrays)
        if key not in self._compiled:
            self._compiled[key] = self._build(arrays)
        exe = self._compiled[key]
        try:
            return exe.cost_analysis()
        except Exception:
            return {}


def load_exported(path):
    """Load a Predictor.export artifact; returns a callable taking numpy
    arrays and returning numpy outputs (runs via jax.export.deserialize —
    no model class needed)."""
    from jax import export as jexport

    with open(path, "rb") as f:
        exported = jexport.deserialize(bytearray(f.read()))

    def run(*inputs):
        arrays = [jnp.asarray(x.data if isinstance(x, Tensor) else x)
                  for x in inputs]
        out = exported.call(*arrays)
        if isinstance(out, (tuple, list)):
            return [np.asarray(jax.device_get(o)) for o in out]
        return np.asarray(jax.device_get(out))

    return run


def create_predictor(config):
    """reference: paddle_infer.create_predictor."""
    return Predictor(config)
