"""paddle_tpu.static — static Program/Executor (compile-and-run).

TPU-native rebuild of the reference's static graph stack
(reference: python/paddle/fluid/framework.py Program/Block/Operator/Variable,
executor.py Executor, backward.py append_backward, compiler.py
CompiledProgram; C++ side paddle/fluid/framework/executor.cc).

Redesign for XLA: a Program is a linear record of op-nodes, each carrying
the same pure-jax impl used by dygraph. ``Executor.run`` does NOT walk ops
one-by-one through a C++ scope like the reference — it *interprets the whole
graph once inside jax.jit*, producing a single fused XLA executable per
(program, feed-shapes) pair, with parameters donated and optimizer updates
fused in (grads come from ``jax.grad`` over the interpreter — no hand-built
grad ops, replacing backward.py's op-by-op transposition).
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor, Parameter, convert_dtype
from .. import dispatch
from .. import monitor as _monitor


# ---------------------------------------------------------------------------
# graph structures

class StaticVar(Tensor):
    """Symbolic variable (reference: framework.py:Variable). Subclasses
    Tensor so layer code paths treat it uniformly; payload is None until the
    Executor materializes it."""

    __slots__ = ("_shape", "_shape2", "_dtype", "program", "is_feed")

    def __init__(self, name, shape, dtype, program, is_feed=False,
                 shape2=None):
        # bypass Tensor.__init__ array coercion
        self.data = None
        self.stop_gradient = True
        self._grad = None
        self._tape_node = None
        self._graph_freed = False
        self.name = name
        self.persistable = False
        self._shape = tuple(shape)
        # second probe shape: symbolic (None/-1) dims get a DIFFERENT
        # placeholder so shape inference can tell static from dynamic dims
        self._shape2 = tuple(shape2) if shape2 is not None else tuple(
            2 if (s is None or s < 0) else s for s in self._shape)
        self._dtype = jnp.dtype(convert_dtype(dtype) or jnp.float32)
        self.program = program
        self.is_feed = is_feed

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    def aval(self):
        shape = tuple(1 if (s is None or s < 0) else s for s in self._shape)
        return jax.ShapeDtypeStruct(shape, self._dtype)

    def aval2(self):
        return jax.ShapeDtypeStruct(self._shape2, self._dtype)

    def __repr__(self):
        return f"StaticVar(name={self.name}, shape={self._shape}, dtype={self._dtype})"


class OpNode:
    """One recorded op (reference: framework.py:Operator/OpDesc)."""

    __slots__ = ("impl", "attrs", "inputs", "outputs", "type")

    def __init__(self, impl, attrs, inputs, outputs, type_=""):
        self.impl = impl
        self.attrs = attrs
        self.inputs = inputs    # list of var names
        self.outputs = outputs  # list of var names
        self.type = type_


class Block:
    """reference: framework.py:Block — holds vars and ops."""

    def __init__(self, program, idx=0):
        self.program = program
        self.idx = idx
        self.vars = {}
        self.ops = []

    def create_var(self, shape, dtype, name=None, is_feed=False):
        name = name or self.program._unique_name("tmp")
        v = StaticVar(name, shape, dtype, self.program, is_feed=is_feed)
        self.vars[name] = v
        return v


class Program:
    """reference: framework.py:Program. One global block (control flow uses
    lax primitives rather than sub-blocks — XLA handles nesting)."""

    _counter = [0]

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.version = 0
        self._name_counter = 0
        self.param_vars = {}      # name -> Parameter (concrete payload)
        self.const_vars = {}      # name -> Tensor (concrete payload)
        self.feed_vars = {}       # name -> StaticVar
        self.rng_vars = []        # names of per-run PRNG key inputs
        self.optimizers = []      # [(Optimizer, loss_var_name)]
        self.random_seed = None
        Program._counter[0] += 1
        self.id = Program._counter[0]

    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[-1]

    def _unique_name(self, stem):
        self._name_counter += 1
        return f"_{self.id}_{stem}_{self._name_counter}"

    def all_parameters(self):
        return list(self.param_vars.values())

    def clone(self, for_test=False):
        """reference: Program.clone(for_test=True) — share vars/params; a
        test clone drops optimizer records (and callers rebuild with
        is_test behavior via Layer.eval())."""
        import copy
        p = Program.__new__(Program)
        p.blocks = self.blocks
        p.version = self.version
        p._name_counter = self._name_counter
        p.param_vars = self.param_vars
        p.const_vars = self.const_vars
        p.feed_vars = self.feed_vars
        p.rng_vars = self.rng_vars
        p.optimizers = [] if for_test else list(self.optimizers)
        p.random_seed = self.random_seed
        Program._counter[0] += 1
        p.id = Program._counter[0]
        return p


_default_main_program = Program()
_default_startup_program = Program()
_program_stack = []


def default_main_program():
    return _program_stack[-1][0] if _program_stack else _default_main_program


def default_startup_program():
    return _program_stack[-1][1] if _program_stack else _default_startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """reference: fluid.program_guard."""
    _program_stack.append((main_program,
                           startup_program or _default_startup_program))
    try:
        yield
    finally:
        _program_stack.pop()


def reset_default_programs():
    global _default_main_program, _default_startup_program
    _default_main_program = Program()
    _default_startup_program = Program()


# ---------------------------------------------------------------------------
# mode switching (reference: paddle.enable_static / fluid default)

def enable_static():
    dispatch.set_static_mode(True)


def disable_static():
    dispatch.set_static_mode(False)


def in_static_mode():
    return dispatch.in_static_mode()


# ---------------------------------------------------------------------------
# feed declaration (reference: fluid.data / layers.data)

def data(name, shape, dtype="float32", lod_level=0):
    prog = default_main_program()
    block = prog.global_block()
    v = StaticVar(name, shape, dtype, prog, is_feed=True)
    block.vars[name] = v
    prog.feed_vars[name] = v
    return v


def make_rng_var():
    """Register a per-run PRNG key input (shape (2,) uint32, the raw
    jax.random.PRNGKey layout). The Executor splits the global key and
    feeds every rng var a fresh subkey on each run, so stochastic ops
    recorded in the graph (dropout, …) re-randomize per run instead of
    baking one mask at record time."""
    prog = default_main_program()
    block = prog.global_block()
    v = StaticVar(prog._unique_name("rng_key"), (2,), jnp.uint32, prog)
    block.vars[v.name] = v
    prog.rng_vars.append(v.name)
    prog.version += 1
    return v


class InputSpec:
    """paddle.static.InputSpec parity (used by jit.save input_spec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name


# ---------------------------------------------------------------------------
# the recorder — installed into paddle_tpu.dispatch

def _as_graph_var(t, block, prog):
    if isinstance(t, StaticVar):
        return t
    if isinstance(t, Parameter):
        name = t.name or f"param_{id(t)}"
        if name not in prog.param_vars:
            prog.param_vars[name] = t
            t.name = name
        return name
    if isinstance(t, Tensor):
        name = prog._unique_name("const")
        prog.const_vars[name] = t
        return name
    # python scalar / numpy
    tt = Tensor(t)
    name = prog._unique_name("const")
    prog.const_vars[name] = tt
    return name


def _record(impl, tensors, attrs, nondiff, n_out, name):
    prog = default_main_program()
    block = prog.current_block()

    in_names, in_avals, in_avals2 = [], [], []
    for t in tensors:
        gv = _as_graph_var(t, block, prog)
        if isinstance(gv, StaticVar):
            in_names.append(gv.name)
            in_avals.append(gv.aval())
            in_avals2.append(gv.aval2())
        else:
            in_names.append(gv)
            holder = prog.param_vars.get(gv)
            if holder is None:
                holder = prog.const_vars[gv]
            payload = holder.data
            av = jax.ShapeDtypeStruct(payload.shape, payload.dtype)
            in_avals.append(av)
            in_avals2.append(av)

    # two shape-inference probes: dims that differ between them are
    # dynamic (batch-like) and stay symbolic in the out vars
    out_avals = jax.eval_shape(lambda *xs: impl(*xs, **attrs), *in_avals)
    out_avals2 = jax.eval_shape(lambda *xs: impl(*xs, **attrs), *in_avals2)
    single = not isinstance(out_avals, (tuple, list))
    outs_seq = (out_avals,) if single else tuple(out_avals)
    outs_seq2 = (out_avals2,) if single else tuple(out_avals2)

    out_vars = []
    for av, av2 in zip(outs_seq, outs_seq2):
        shape = tuple(None if a != b else a
                      for a, b in zip(av.shape, av2.shape))
        v = StaticVar(prog._unique_name(name or "op"), shape, av.dtype,
                      prog, shape2=av2.shape)
        block.vars[v.name] = v
        v.stop_gradient = nondiff
        out_vars.append(v)

    block.ops.append(OpNode(impl, attrs, in_names,
                            [v.name for v in out_vars], type_=name))
    prog.version += 1
    return out_vars[0] if single else tuple(out_vars)


dispatch.install_static_recorder(_record)


# ---------------------------------------------------------------------------
# backward / optimizer recording (reference: backward.py append_backward +
# optimizer.minimize static path)

def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Marks the loss for gradient computation. Returns [] — gradients are
    produced by jax.grad over the program interpreter inside Executor.run
    (no explicit grad ops appended, unlike reference backward.py)."""
    prog = loss.program if isinstance(loss, StaticVar) else \
        default_main_program()
    prog._loss_name = loss.name
    return []


def record_optimizer(optimizer, loss):
    """Called by Optimizer.minimize under static mode."""
    prog = loss.program if isinstance(loss, StaticVar) else \
        default_main_program()
    prog.optimizers.append((optimizer, loss.name))
    prog.version += 1
    return None, None


# ---------------------------------------------------------------------------
# Executor

class Scope:
    """reference: framework/scope.cc — here just a name→Tensor dict; the
    actual device residency is owned by XLA."""

    def __init__(self):
        self.vars = {}

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


class Executor:
    """reference: executor.py:Executor — but run() compiles the WHOLE
    program (+ grads + optimizer update) into one XLA executable, cached per
    feed signature.

    Pipelining surface (the MXU-feeding knobs):

    * ``bucket=True`` (+ ``buckets=[...]``) — ragged feed batches pad up
      to a closed bucket set instead of minting a new executable per
      shape (per-example fetches are sliced back to the real length).
    * ``async_fetch=True`` / ``fetch_period=k`` — run() returns the
      PREVIOUS step's fetches (already computed, so ``device_get`` never
      blocks on the step critical path); ``flush_fetches()`` drains the
      last pending ones after the loop.
    * ``warmup()`` — AOT ``lower().compile()`` of a (program, feed-spec)
      executable before the first step.
    """

    def __init__(self, place=None):
        self.place = place
        self._cache = {}
        self._seen_base = set()   # (program, fetches, mesh) combos compiled
        self._pending_fetches = None
        self._async_runs = 0
        self._mem_warned = False  # offload-on-static fallback warned once

    @staticmethod
    def _mesh_sig(dp_mesh, dp_requested):
        """Mesh identity for the executable cache key. A plain run and a
        with_data_parallel run with identical feed shapes produce
        DIFFERENT executables (sharded feeds + GSPMD partitioning) and
        must never collide; absence of a mesh is part of the identity."""
        if dp_mesh is not None:
            return (tuple(int(d.id) for d in dp_mesh.devices.flat),
                    tuple(dp_mesh.axis_names))
        if dp_requested:
            return "dp"  # with_data_parallel on a single device
        return None

    @staticmethod
    def _param_slot_names(program):
        param_names = sorted(program.param_vars)
        opt_entries = program.optimizers
        slot_names = []
        for oi, (opt, _) in enumerate(opt_entries):
            trainables = [p for p in program.param_vars.values()
                          if not p.stop_gradient]
            opt._parameter_list = opt._parameter_list or trainables
            opt._ensure_all_slots()
            for pid, slots in opt._accumulators.items():
                for sname in slots:
                    slot_names.append((oi, pid, sname))
        return param_names, opt_entries, slot_names

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, scope=None, bucket=False, buckets=None,
            pad_mode="repeat", async_fetch=False, fetch_period=None,
            nan_guard=None, mesh_plan=None, memory=None):
        try:
            return self._run_impl(program, feed, fetch_list, return_numpy,
                                  scope, bucket, buckets, pad_mode,
                                  async_fetch, fetch_period, nan_guard,
                                  mesh_plan, memory)
        except BaseException as e:
            # unhandled crash: leave the flight-recorder artifact (last
            # spans + counters + active HLO) before the stack unwinds.
            # RESOURCE_EXHAUSTED gets the richer OOM postmortem: the
            # flight bundle then carries the ranked memory-contributor
            # ledger alongside the op ledger.
            if _monitor.enabled():
                if not _monitor.memory.handle_oom(e, where="executor.run"):
                    _monitor.trace.flight_record("executor_crash")
            raise

    def _run_impl(self, program, feed, fetch_list, return_numpy, scope,
                  bucket, buckets, pad_mode, async_fetch, fetch_period,
                  nan_guard, mesh_plan=None, memory=None):
        program = program or default_main_program()
        mem_remat = None
        mem_key = "none"
        if memory is not None:
            from .. import memory_plan as _mp
            mem_pol = _mp.resolve(memory)
            if mem_pol == "auto":
                raise ValueError(
                    'memory="auto" is a loop-level knob: use '
                    'train_from_dataset(memory="auto"), or call '
                    "memory_plan.plan_memory(auto=True) yourself and "
                    "pass the decision's policy here")
            if mem_pol is not None:
                mem_key = _mp.policy_key(mem_pol)
                mem_remat = mem_pol.remat
                if mem_pol.offload or mem_pol.master_weights:
                    # a static Program carries params and slots as
                    # explicit (donated) executable arguments, so paging
                    # them to host would just re-upload everything each
                    # run with no HBM saving, and the bf16 view dtype is
                    # an arena-trace feature — remat is the mechanism
                    # that applies here. Fall back loudly, once.
                    if not self._mem_warned:
                        self._mem_warned = True
                        import warnings
                        warnings.warn(
                            "Executor.run(memory=): offload/"
                            "master_weights only apply to the eager "
                            "arena path (hapi.Model.fit / "
                            "optimizer.step); applying the remat part "
                            "only", RuntimeWarning)
                    if _monitor.enabled():
                        _monitor.counter(
                            "executor.memory_policy_fallback").inc()
        if isinstance(nan_guard, str):
            from ..resilience.guard import NaNGuard
            nan_guard = NaNGuard(nan_guard)
        dp_mesh = None
        dp_requested = False
        if isinstance(program, CompiledProgram):
            dp_requested = program._data_parallel
            if program._data_parallel:
                dp_mesh = program._dp_mesh
            program = program.program
        feed = feed or {}
        fetch_list = fetch_list or []
        if not program.global_block().ops:
            return []  # startup program: params already init'd eagerly

        fetch_names = [v.name if isinstance(v, StaticVar) else str(v)
                       for v in fetch_list]

        # normalize feeds on the HOST: shapes/dtypes for the cache key
        # come straight from the numpy/jax arrays — no jnp.asarray (and
        # its device transfer) before we know whether this is a cache
        # hit. jit/device_put convert on the way in exactly once.
        feed_span = _monitor.trace.span("executor.feed_prep")
        feed_span.__enter__()
        feed_arrays = {}
        for k, v in feed.items():
            if isinstance(v, Tensor):
                v = v.data
            if not isinstance(v, (np.ndarray, jax.Array)):
                v = np.asarray(v)
            if isinstance(v, np.ndarray) and v.dtype in (
                    np.float64, np.int64, np.uint64):
                # mirror jnp.asarray's x64-off canonicalization so the
                # cache key matches what the executable will receive
                v = v.astype({np.dtype(np.float64): np.float32,
                              np.dtype(np.int64): np.int32,
                              np.dtype(np.uint64): np.uint32}[v.dtype])
            feed_arrays[k] = v

        real_n = padded_n = None
        if bucket:
            from ..io.bucketing import pad_feed_dict
            feed_arrays, real_n, padded_n = pad_feed_dict(
                feed_arrays, buckets=buckets, mode=pad_mode)
            if padded_n is not None and _monitor.enabled():
                _monitor.counter("executor.bucket_pad").inc()

        plan = None
        if mesh_plan is not None:
            from ..parallel import planner as _planner
            plan = _planner.resolve(mesh_plan, mesh=dp_mesh)

        if plan is not None:
            # planner-driven layout: every feed shards under the plan's
            # data axes (replicated when the batch dim doesn't divide),
            # every param takes its rule-matched spec — this is the
            # generalization of with_data_parallel to dp×tp(×sp) hybrids
            for k, a in feed_arrays.items():
                feed_arrays[k] = plan.shard_input(a)
            for n, holder in program.param_vars.items():
                holder.data = plan.place(n, holder.data)
        elif dp_mesh is not None:
            # CompiledProgram.with_data_parallel: batch-shard every feed
            # over the mesh; params ride replicated and GSPMD partitions
            # the compiled step (reference: compiler.py graph replication)
            from jax.sharding import NamedSharding, PartitionSpec as P
            ndev = dp_mesh.devices.size
            for k, a in feed_arrays.items():
                if a.ndim >= 1 and a.shape[0] % ndev == 0:
                    spec = P(*(("dp",) + (None,) * (a.ndim - 1)))
                else:
                    if a.ndim >= 1:
                        raise ValueError(
                            f"with_data_parallel: feed '{k}' batch dim "
                            f"{a.shape[0]} is not divisible by the "
                            f"{ndev}-device mesh")
                    spec = P()
                feed_arrays[k] = jax.device_put(
                    a, NamedSharding(dp_mesh, spec))
            rep = NamedSharding(dp_mesh, P())
            for n, holder in program.param_vars.items():
                cur = getattr(holder.data, "sharding", None)
                if cur != rep:
                    holder.data = jax.device_put(holder.data, rep)

        feed_span.__exit__(None, None, None)

        param_names, opt_entries, slot_names = \
            self._param_slot_names(program)

        base_key = (program.id, program.version, tuple(fetch_names),
                    (plan.plan_key() if plan is not None
                     else self._mesh_sig(dp_mesh, dp_requested)),
                    nan_guard is not None, mem_key)
        key = base_key + (tuple(sorted((k, tuple(a.shape), str(a.dtype))
                                       for k, a in feed_arrays.items())),)
        if _monitor.enabled():
            _monitor.counter("executor.run").inc()
            _monitor.counter("executor.cache_hit" if key in self._cache
                             else "executor.cache_miss").inc()
            if key not in self._cache and base_key in self._seen_base:
                # same program+fetches+mesh, new feed shapes: the
                # avoidable-recompile series bucketing exists to flatten
                _monitor.counter("executor.recompile").inc()
        new_key = key not in self._cache
        if new_key:
            self._seen_base.add(base_key)
            import time as _time
            _t0_compile = _time.perf_counter()
            with _monitor.trace.span("executor.compile",
                                     program=program.id,
                                     version=program.version):
                self._cache[key] = self._compile(
                    program, fetch_names, sorted(feed_arrays),
                    param_names, slot_names,
                    nan_guard=nan_guard is not None, remat=mem_remat)
            if _monitor.enabled():
                # wall seconds spent minting executables — the compile
                # category of the goodput ledger (monitor/step.py)
                _monitor.counter("executor.compile_s").inc(
                    _time.perf_counter() - _t0_compile)
        compiled = self._cache[key]

        param_vals = [program.param_vars[n].data for n in param_names]
        slot_vals = [opt_entries[oi][0]._accumulators[pid][sn].data
                     for oi, pid, sn in slot_names]
        lr_vals = [opt._lr_tensor.data for opt, _ in opt_entries]
        feed_vals = [feed_arrays[k] for k in sorted(feed_arrays)]
        # fresh subkeys per run for recorded stochastic ops (dropout, …)
        from .. import random as prandom
        rng_vals = (list(prandom.split_keys(len(program.rng_vars)))
                    if program.rng_vars else [])

        if new_key and _monitor.enabled():
            # the first call pays the XLA compile either way; doing it
            # AOT (lower+compile) yields a Compiled whose
            # cost_analysis()/memory_analysis() feed the xla.* gauges
            # and the flight recorder's HLO dump. Falls back to the
            # jitted entry untouched if anything goes wrong.
            with _monitor.trace.span("executor.aot_capture"):
                compiled = self._cache[key] = _monitor.xla.aot_capture(
                    compiled, f"exec.p{program.id}v{program.version}",
                    (feed_vals, param_vals, slot_vals, lr_vals, rng_vals))

        finite_flag = None
        with _monitor.trace.span("executor.execute",
                                 program=program.id):
            if nan_guard is not None:
                fetches, new_params, new_slots, finite_flag = compiled(
                    feed_vals, param_vals, slot_vals, lr_vals, rng_vals)
            else:
                fetches, new_params, new_slots = compiled(
                    feed_vals, param_vals, slot_vals, lr_vals, rng_vals)

        for n, v in zip(param_names, new_params):
            program.param_vars[n].data = v
        for (oi, pid, sn), v in zip(slot_names, new_slots):
            opt_entries[oi][0]._accumulators[pid][sn].data = v

        if finite_flag is not None:
            # the compiled step already where-selected the old params back
            # on a non-finite step (skip semantics in-jit); the host sync
            # here accounts for it and drives rollback/raise policies.
            nan_guard.note_device_flag(
                bool(np.asarray(jax.device_get(finite_flag))),
                program=program, where="executor")

        if async_fetch or fetch_period:
            # non-blocking fetch path: hand back the PREVIOUS step's
            # fetches (their device computation finished while this step
            # was being dispatched) so the host never sits in device_get
            # on the step critical path. fetch_period=k additionally
            # materializes only every k-th call.
            period = max(1, int(fetch_period or 1))
            prev = self._pending_fetches
            self._pending_fetches = (fetches, real_n, padded_n,
                                     return_numpy)
            self._async_runs += 1
            if _monitor.enabled():
                _monitor.counter("executor.fetch_async").inc()
            if self._async_runs % period != 0 or prev is None:
                if _monitor.enabled():
                    _monitor.counter("executor.fetch_skipped").inc()
                return None
            with _monitor.trace.span("executor.fetch", mode="async"):
                return self._materialize(prev)

        if _monitor.enabled() and return_numpy and fetches:
            # the blocking device_get this sits in is exactly what
            # async_fetch removes from the per-step path
            _monitor.counter("executor.fetch_blocking").inc()
        with _monitor.trace.span("executor.fetch", mode="sync"):
            return self._materialize((fetches, real_n, padded_n,
                                      return_numpy))

    @staticmethod
    def _materialize(pending):
        fetches, real_n, padded_n, return_numpy = pending
        if real_n is not None:
            # bucketing padded the feeds: slice per-example fetches back
            # to the real batch length (scalar reductions pass through)
            fetches = [f[:real_n]
                       if getattr(f, "ndim", 0) >= 1 and
                       f.shape[0] == padded_n else f
                       for f in fetches]
        if return_numpy:
            return [np.asarray(jax.device_get(f)) for f in fetches]
        return [Tensor(f) for f in fetches]

    def flush_fetches(self):
        """Drain the pending async fetches (call once after the training
        loop; returns None when nothing is pending)."""
        prev, self._pending_fetches = self._pending_fetches, None
        self._async_runs = 0
        if prev is None:
            return None
        return self._materialize(prev)

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           prefetch=0, bucket=False, buckets=None,
                           checkpoint=None, save_steps=None,
                           auto_resume=False, nan_guard=None,
                           grad_sync=None, flat_arena=None,
                           mesh_plan=None, memory=None):
        """reference executor.py:train_from_dataset — run the program
        over every batch a fluid.dataset yields. The reference spawns
        C++ DataFeed threads; here each host-assembled MultiSlot batch
        goes through the same compiled run() path as any feed (one
        executable, cached per feed signature).

        ``prefetch=N`` stages the next N feed dicts on device via a
        background thread while the current step runs; ``bucket=True``
        pads ragged final batches up to the bucket set so the epoch
        doesn't recompile on its tail.

        Resilience: ``checkpoint`` (an io.CheckpointManager or a
        directory path) enables atomic program checkpoints every
        ``save_steps`` batches and on SIGTERM/SIGINT; ``auto_resume=True``
        restores the newest valid checkpoint and skips already-trained
        batches; ``nan_guard`` (a resilience.NaNGuard or policy string)
        guards every step.

        ``grad_sync`` ("exact"|"quantized"|"overlap" or a
        parallel.overlap.GradSyncScheduler) attaches a gradient-sync
        scheduler to every optimizer the program recorded (see
        docs/performance.md "Communication overlap & quantized
        sync"); ``flat_arena=True`` turns on the zero-copy flat
        parameter arena for every recorded Adam/AdamW (see
        docs/performance.md "Flat parameter arena").

        ``mesh_plan`` (a parallel.planner.MeshPlan, rule tuple, or
        "auto") lays the program's params and every feed batch out
        under the plan — same knob as hapi.Model.fit(mesh_plan=); see
        docs/parallelism.md.

        ``memory`` ("none"/"dots"/"full", a policy dict, a
        memory_plan.MemoryPolicy, or "auto") applies a memory policy to
        the compiled program — on this surface the remat mechanism
        (offload/master_weights fall back with a warning, see
        Executor.run). "auto" compiles the first batch as the baseline,
        asks memory_plan.plan_memory(auto=True) for the cheapest policy
        that fits the HBM budget, and runs the rest of the dataset
        under the pick (one recompile). See docs/performance.md
        "Memory as a planned resource"."""
        if dataset is None:
            raise RuntimeError("dataset is required for train_from_dataset")
        fetch_list = fetch_list or []
        fetch_info = fetch_info or [getattr(v, "name", str(v))
                                    for v in fetch_list]

        prog = program or default_main_program()
        real_prog = prog.program if isinstance(prog, CompiledProgram) else prog
        if grad_sync is not None:
            for _opt, _ in getattr(real_prog, "optimizers", []):
                _opt.set_grad_sync(grad_sync)
        if flat_arena is not None:
            for _opt, _ in getattr(real_prog, "optimizers", []):
                _opt.set_flat_arena(flat_arena)
        if mesh_plan is not None:
            from ..parallel import planner as _planner
            mesh_plan = _planner.resolve(mesh_plan)
        mem_pol = None
        mem_auto = False
        if memory is not None:
            from .. import memory_plan as _mp
            mem_pol = _mp.resolve(memory)
            if mem_pol == "auto":
                mem_auto = True
                mem_pol = None  # first batch runs (and costs) baseline
        cm = None
        if checkpoint is not None:
            from ..io import CheckpointManager
            cm = (checkpoint if isinstance(checkpoint, CheckpointManager)
                  else CheckpointManager(checkpoint))
        if isinstance(nan_guard, str):
            from ..resilience.guard import NaNGuard
            nan_guard = NaNGuard(nan_guard, checkpoint_manager=cm)
        if nan_guard is not None and \
                nan_guard.checkpoint_manager is None and cm is not None:
            nan_guard.checkpoint_manager = cm

        from ..resilience import faults as _faults
        from ..resilience._common import record as _rrecord
        start_step = 0
        if auto_resume and cm is not None:
            latest = cm.latest_step()
            if latest is not None:
                state = cm.restore(program=real_prog, step=latest)
                start_step = int(state.get("step", latest)) + 1
                _rrecord("auto_resume", step=start_step,
                         checkpoint_step=latest, where="executor")

        handler = None
        if cm is not None:
            from ..resilience.preempt import PreemptionHandler
            handler = PreemptionHandler().install()
            # real SIGTERM → flush a final program save from the signal
            # path (the loop's boundary save may never come)
            handler.attach(cm, save_fn=lambda s: cm.save(
                s, program=real_prog))

        batches = dataset._batches()
        if prefetch:
            from ..io.prefetch import prefetch_to_device
            batches = prefetch_to_device(batches, size=prefetch)
        try:
            for i, batch in enumerate(batches):
                if i < start_step:
                    continue  # auto_resume fast-forward
                if _faults.enabled():
                    _faults.maybe_raise("host_loss", i)
                outs = self.run(program, feed=batch, fetch_list=fetch_list,
                                scope=scope, bucket=bucket, buckets=buckets,
                                nan_guard=nan_guard, mesh_plan=mesh_plan,
                                memory=mem_pol)
                if mem_auto:
                    # the baseline batch just compiled (its aot capture
                    # feeds the predicted-peak model) — pick once, run
                    # the remainder under the chosen policy
                    mem_auto = False
                    from .. import memory_plan as _mp
                    if _monitor.enabled():
                        mem_pol = _mp.plan_memory(auto=True)["policy"]
                    else:
                        import warnings
                        warnings.warn(
                            'memory="auto" needs the monitor enabled '
                            "(the compiled step's aot capture feeds the "
                            "predicted-peak model); keeping the "
                            "baseline policy", RuntimeWarning)
                if handler is not None:
                    handler.notify_step(i)
                if debug and fetch_list and i % max(print_period, 1) == 0:
                    msg = ", ".join(f"{n}={np.asarray(o).ravel()[:1]}"
                                    for n, o in zip(fetch_info, outs))
                    print(f"batch {i}: {msg}", flush=True)
                preempted = (handler is not None and handler.triggered) or \
                    (_faults.enabled() and _faults.fire("preempt", i))
                if cm is not None and (
                        preempted or
                        (save_steps and (i + 1) % save_steps == 0)) and (
                        handler is None or handler.flushed_step != i):
                    cm.save(i, program=real_prog)
                    if preempted:
                        _rrecord("preempt_save", step=i, where="executor")
                if preempted:
                    break
        finally:
            if handler is not None:
                handler.uninstall()

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           prefetch=0, bucket=False, buckets=None):
        """reference executor.py:infer_from_dataset — same loop; the
        program carries no optimizer ops so run() only evaluates."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period, prefetch=prefetch,
                                       bucket=bucket, buckets=buckets)

    def warmup(self, program=None, feed_specs=None, fetch_list=None,
               bucket=False, buckets=None):
        """AOT-compile the (program, feed-spec) executable before the
        first step: ``jit(...).lower(...).compile()`` over abstract
        ShapeDtypeStructs, cached under the same key ``run`` computes —
        the first real step starts on a warm executable (and, with the
        persistent compilation cache enabled, a rerun of the same
        process skips XLA entirely).

        ``feed_specs`` maps feed name → (shape, dtype) | InputSpec | a
        template array. Returns the cache key."""
        program = program or default_main_program()
        dp_mesh = None
        dp_requested = False
        if isinstance(program, CompiledProgram):
            dp_requested = program._data_parallel
            if program._data_parallel:
                dp_mesh = program._dp_mesh
            program = program.program
        if not program.global_block().ops:
            return None
        fetch_list = fetch_list or []
        fetch_names = [v.name if isinstance(v, StaticVar) else str(v)
                       for v in fetch_list]

        specs = {}
        for k, v in (feed_specs or {}).items():
            if isinstance(v, InputSpec):
                shape, dtype = v.shape, v.dtype
            elif hasattr(v, "shape") and hasattr(v, "dtype"):
                shape, dtype = v.shape, v.dtype
            else:
                shape, dtype = v
            shape = tuple(int(s) for s in shape)
            if bucket and shape:
                from ..io.bucketing import next_bucket
                shape = (next_bucket(shape[0], buckets),) + shape[1:]
            specs[k] = (shape, jnp.dtype(convert_dtype(dtype) or dtype))

        param_names, opt_entries, slot_names = \
            self._param_slot_names(program)
        base_key = (program.id, program.version, tuple(fetch_names),
                    self._mesh_sig(dp_mesh, dp_requested), False, "none")
        key = base_key + (tuple(sorted((k, s, str(d))
                                       for k, (s, d) in specs.items())),)
        if key in self._cache:
            return key

        if dp_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            ndev = dp_mesh.devices.size

            def sds(shape, dtype):
                if len(shape) >= 1 and shape[0] % ndev == 0:
                    spec = P(*(("dp",) + (None,) * (len(shape) - 1)))
                else:
                    spec = P()
                return jax.ShapeDtypeStruct(
                    shape, dtype, sharding=NamedSharding(dp_mesh, spec))

            rep = NamedSharding(dp_mesh, P())

            def psds(a):
                return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep)
        else:
            def sds(shape, dtype):
                return jax.ShapeDtypeStruct(shape, dtype)

            def psds(a):
                return jax.ShapeDtypeStruct(a.shape, a.dtype)

        feed_order = sorted(specs)
        feed_structs = [sds(*specs[k]) for k in feed_order]
        param_structs = [psds(program.param_vars[n].data)
                         for n in param_names]
        slot_structs = [psds(opt_entries[oi][0]._accumulators[pid][sn].data)
                        for oi, pid, sn in slot_names]
        lr_structs = [psds(opt._lr_tensor.data) for opt, _ in opt_entries]
        rng_structs = [jax.ShapeDtypeStruct((2,), jnp.uint32)
                       for _ in program.rng_vars]

        jitted = self._compile(program, fetch_names, feed_order,
                               param_names, slot_names)
        compiled = jitted.lower(feed_structs, param_structs, slot_structs,
                                lr_structs, rng_structs).compile()
        self._seen_base.add(base_key)
        self._cache[key] = compiled
        if _monitor.enabled():
            _monitor.counter("executor.aot_warmup").inc()
            _monitor.xla.capture(
                f"exec.p{program.id}v{program.version}", compiled)
        return key

    def _compile(self, program, fetch_names, feed_order, param_names,
                 slot_names, nan_guard=False, remat=None):
        # remat: canonical policy from memory_plan._canon_remat — a name
        # ("dots"/"full") checkpoints the whole fwd pass under that
        # jax.checkpoint policy; per-layer rules degrade to "full" here
        # (a graph Program has no Layer boundaries to match against)
        ckpt_policy = None
        if remat is not None and isinstance(remat, str):
            from ..memory_plan import checkpoint_policy
            ckpt_policy = checkpoint_policy(remat)
        if _monitor.enabled():
            _monitor.counter("executor.compile").inc()
            _monitor.emit(kind="executor_compile", program_id=program.id,
                          program_version=program.version,
                          n_ops=len(program.global_block().ops),
                          n_params=len(param_names),
                          fetches=list(fetch_names))
        ops = list(program.global_block().ops)
        const_vals = {n: t.data for n, t in program.const_vars.items()}
        opt_entries = program.optimizers
        rng_names = list(program.rng_vars)
        # register the graph-op types as attributable "op" scopes so a
        # profile.report() over a static Program's executable credits
        # flops to op types (compile-time cost, one dict write per type)
        for op in ops:
            _monitor.profile.register_scope(op.type or "op", "op")

        def interpret(env):
            for op in ops:
                ins = [env[n] for n in op.inputs]
                # named_scope tags the lowered HLO ops with the graph op
                # type, so an XLA profile/HLO dump reads as the Program
                with jax.named_scope(op.type or "op"):
                    outs = op.impl(*ins, **op.attrs)
                if isinstance(outs, (tuple, list)):
                    for n, o in zip(op.outputs, outs):
                        env[n] = o
                else:
                    env[op.outputs[0]] = outs
            return env

        def forward(feed_vals, param_vals, rng_vals):
            env = dict(const_vals)
            env.update(zip(feed_order, feed_vals))
            env.update(zip(param_names, param_vals))
            env.update(zip(rng_names, rng_vals))
            env = interpret(env)
            return env

        trainable_idx = [i for i, n in enumerate(param_names)
                         if not program.param_vars[n].stop_gradient]

        def run_fn(feed_vals, param_vals, slot_vals, lr_vals, rng_vals):
            new_params = list(param_vals)
            new_slots = list(slot_vals)
            fetches = None
            finite = jnp.asarray(True) if nan_guard else None
            for oi, (opt, loss_name) in enumerate(opt_entries):
                # grads of loss wrt trainable params via jax.grad over the
                # interpreter (replaces reference append_backward grad ops);
                # the forward env rides along as aux so fetches don't pay a
                # second forward pass.
                def loss_of(tp):
                    pv = list(new_params)
                    for j, i in enumerate(trainable_idx):
                        pv[i] = tp[j]
                    env2 = forward(feed_vals, pv, rng_vals)
                    return jnp.sum(env2[loss_name]), env2

                tp = [new_params[i] for i in trainable_idx]
                if remat is not None:
                    # rematerialized backward: the whole forward is one
                    # jax.checkpoint region. The aux is NARROWED to the
                    # fetches + loss — returning the whole env would pin
                    # every intermediate as a residual and undo the
                    # remat. Exact: same primals, recomputed not stored.
                    def loss_of_ckpt(tp):
                        pv = list(new_params)
                        for j, i in enumerate(trainable_idx):
                            pv[i] = tp[j]
                        env2 = forward(feed_vals, pv, rng_vals)
                        return jnp.sum(env2[loss_name]), (
                            [env2[n] for n in fetch_names],
                            env2[loss_name])
                    grads, (fvals, lval) = jax.grad(
                        jax.checkpoint(loss_of_ckpt, policy=ckpt_policy),
                        has_aux=True)(tp)
                else:
                    grads, env = jax.grad(loss_of, has_aux=True)(tp)
                    fvals = [env[n] for n in fetch_names]
                    lval = env[loss_name]
                if fetches is None:
                    fetches = fvals
                if nan_guard:
                    from ..amp import tree_all_finite
                    finite = jnp.logical_and(
                        finite, tree_all_finite(list(grads) + [lval]))

                # reference order: clip raw grads first, then regularize
                params_grads = [(i, program.param_vars[param_names[i]],
                                 grads[j])
                                for j, i in enumerate(trainable_idx)]
                if opt._grad_clip is not None:
                    clipped = opt._grad_clip([(p, g)
                                              for _, p, g in params_grads])
                    params_grads = [(i, p, g) for (i, p, _), (_, g) in
                                    zip(params_grads, clipped)]
                from ..regularizer import WeightDecayRegularizer
                regularized = []
                for i, p, g in params_grads:
                    reg = p.regularizer or opt._regularization
                    if isinstance(reg, WeightDecayRegularizer):
                        g = g + reg.grad_term(new_params[i])
                    regularized.append((i, p, g))
                params_grads = regularized
                lr = lr_vals[oi]
                arena = getattr(opt, "_arena", None)
                if arena is not None and getattr(opt, "_flat_arena",
                                                 False):
                    # flat-arena update: params stay per-leaf carried
                    # state (the Program's contract) but m/v/pow slots
                    # live flat — see optimizer.arena.static_apply
                    from ..optimizer.arena import static_apply
                    aid = id(arena)
                    sv = {sn: new_slots[k]
                          for k, (o2, pid, sn) in enumerate(slot_names)
                          if o2 == oi and pid == aid}
                    pv = {id(p): new_params[i]
                          for i, p, _ in params_grads}
                    new_by_pid, sv_new = static_apply(
                        opt, [(p, g) for _, p, g in params_grads],
                        pv, sv, lr)
                    for i, p, _ in params_grads:
                        if id(p) in new_by_pid:
                            new_params[i] = new_by_pid[id(p)]
                    for k, (o2, pid, sn) in enumerate(slot_names):
                        if o2 == oi and pid == aid and sn in sv_new:
                            new_slots[k] = sv_new[sn]
                    continue
                for i, p, g in params_grads:
                    slots = {sn: new_slots[k]
                             for k, (o2, pid, sn) in enumerate(slot_names)
                             if o2 == oi and pid == id(p)}
                    np_, ns_ = opt._rule(new_params[i], g, slots, lr)
                    new_params[i] = np_
                    for k, (o2, pid, sn) in enumerate(slot_names):
                        if o2 == oi and pid == id(p) and sn in ns_:
                            new_slots[k] = ns_[sn]
            if fetches is None:
                env = forward(feed_vals, param_vals, rng_vals)
                fetches = [env[n] for n in fetch_names]
                if nan_guard:
                    from ..amp import tree_all_finite
                    finite = tree_all_finite(fetches)
            if nan_guard:
                # in-jit skip: a non-finite step keeps the pre-step state
                # (same select scheme as amp.GradScaler.step), and the
                # flag rides out for host-level policy enforcement
                new_params = [jnp.where(finite, nv, ov)
                              for nv, ov in zip(new_params, param_vals)]
                new_slots = [jnp.where(finite, nv, ov)
                             for nv, ov in zip(new_slots, slot_vals)]
                return fetches, new_params, new_slots, finite
            return fetches, new_params, new_slots

        return jax.jit(run_fn, donate_argnums=(1, 2))

    def close(self):
        self._cache.clear()
        self._seen_base.clear()
        self._pending_fetches = None
        self._async_runs = 0


# ---------------------------------------------------------------------------
# CompiledProgram (reference: compiler.py) — on TPU, compilation happens in
# Executor.run already; CompiledProgram adds device-mesh data parallelism.

class BuildStrategy:
    def __init__(self):
        self.memory_optimize = True
        self.enable_inplace = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


class CompiledProgram:
    """reference: compiler.py:CompiledProgram.with_data_parallel. The
    reference replicates the SSA graph per GPU and all-reduces gradients;
    here with_data_parallel builds a 1-axis device mesh and Executor.run
    shards every feed on its batch dim over it — XLA GSPMD partitions the
    whole compiled step (grad all-reduces included), which is the TPU
    shape of the same feature."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()
        self._data_parallel = False
        self._dp_mesh = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        from jax.sharding import Mesh
        devices = list(places) if places and not isinstance(
            places[0], (str, int)) else jax.devices()
        if len(devices) > 1:
            self._dp_mesh = Mesh(np.array(devices), ("dp",))
        self._data_parallel = True
        return self

    def __getattr__(self, item):
        return getattr(self.program, item)


class ParallelExecutor:
    """reference: parallel_executor.py — multi-device execution. Wraps the
    program in CompiledProgram.with_data_parallel so feeds batch-shard
    over all devices and GSPMD partitions the compiled step (the XLA
    replacement for the reference's SSA multi-device executor)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 **kwargs):
        self._exe = Executor()
        prog = main_program or default_main_program()
        self._program = CompiledProgram(prog).with_data_parallel(
            loss_name=loss_name)

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


# name scope parity
@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: backward.py:gradients — grads of targets wrt inputs.
    Dygraph: delegates to autograd.grad; static mode: gradients are
    produced inside Executor.run via jax.grad over the interpreter, so
    this marks the loss exactly like append_backward."""
    from .. import dispatch
    if not dispatch.in_static_mode():
        from ..autograd import grad as _grad
        t = targets if isinstance(targets, (list, tuple)) else [targets]
        i = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        tg = target_gradients
        if tg is not None and not isinstance(tg, (list, tuple)):
            tg = [tg]
        out = _grad(t, i, grad_outputs=tg)
        return list(out) if isinstance(out, (list, tuple)) else [out]
    append_backward(targets if not isinstance(targets, (list, tuple))
                    else targets[0])
    return []
