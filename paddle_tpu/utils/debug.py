"""paddle_tpu.utils.debug — nan/inf guards, assertions, printing.

TPU-native rebuild of the reference's debug aids
(reference: check_nan_inf in framework/details/nan_inf_utils,
layers/control_flow.py Print/Assert ops). On TPU, `jax.debug.print` /
`jax.config.jax_debug_nans` provide the in-compiled-graph equivalents.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor


def check_nan_inf(x, name="tensor", raise_error=True):
    """Host-side check (eager). Inside jit prefer nan_guard/debug_print."""
    data = x.data if isinstance(x, Tensor) else x
    import numpy as np
    arr = np.asarray(jax.device_get(data))
    bad = not np.isfinite(arr).all()
    if bad and raise_error:
        raise FloatingPointError(
            f"nan/inf detected in {name}: nan={np.isnan(arr).sum()}, "
            f"inf={np.isinf(arr).sum()}")
    return bad


def enable_nan_guard(enable=True):
    """Failure-detection mode: XLA checks every primitive output for NaN
    (reference: FLAGS_check_nan_inf)."""
    jax.config.update("jax_debug_nans", enable)


def Print(x, message="", summarize=20):
    """reference: layers/control_flow.py Print op — works inside jit."""
    data = x.data if isinstance(x, Tensor) else x
    jax.debug.print(message + " {x}", x=data)
    return x


def Assert(cond, data=None, summarize=20):
    """reference: Assert op — eager check; inside jit use checkify."""
    c = cond.data if isinstance(cond, Tensor) else cond
    import numpy as np
    if not bool(np.asarray(jax.device_get(c)).all()):
        raise AssertionError(f"Assert failed; data={data}")
