"""paddle_tpu.utils.log — framework logging.

Rebuild of the reference's logging helpers (reference:
python/paddle/fluid/log_helper.py get_logger — a configured
``logging.Logger`` per subsystem that doesn't propagate to root).
"""
from __future__ import annotations

import logging
import os

_loggers = {}

_FMT = "%(asctime)s %(levelname)s [%(name)s] %(message)s"


def get_logger(name="paddle_tpu", level=None, fmt=_FMT):
    """A configured, non-propagating logger (reference:
    log_helper.py:get_logger). Level defaults to $PADDLE_TPU_LOG_LEVEL or
    INFO."""
    if name in _loggers:
        logger = _loggers[name]
        if level is not None:
            logger.setLevel(level)
        return logger
    logger = logging.getLogger(name)
    if level is None:
        level = getattr(logging,
                        os.environ.get("PADDLE_TPU_LOG_LEVEL", "INFO"),
                        logging.INFO)
    logger.setLevel(level)
    logger.propagate = False
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(fmt))
        logger.addHandler(handler)
    _loggers[name] = logger
    return logger


logger = get_logger()


def set_level(level):
    """Set the level on every framework logger at once."""
    if isinstance(level, str):
        level = getattr(logging, level.upper())
    for lg in _loggers.values():
        lg.setLevel(level)
