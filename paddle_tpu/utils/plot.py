"""paddle.utils.plot parity (reference: python/paddle/utils/plot.py) —
the Ploter training-curve helper. Falls back to silent data collection
when matplotlib/display is unavailable (headless TPU hosts), matching
the reference's disable-on-no-display behavior."""
import os

__all__ = ["Ploter", "PlotData"]


class PlotData:
    """reference plot.py:PlotData."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)


class Ploter:
    """reference plot.py:Ploter — collect (step, value) per named curve
    and plot them together. Plotting needs matplotlib + a display; data
    collection always works."""

    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {t: PlotData() for t in args}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT", "")
        try:  # pragma: no cover - environment dependent
            import matplotlib.pyplot as plt
            self.plt = plt
        except Exception:
            self.plt = None

    def __plot_is_disabled__(self):
        return self.plt is None or self.__disable_plot__.lower() == "true"

    def append(self, title, step, value):
        if title not in self.__plot_data__:
            raise ValueError(f"no title named {title!r}; known: "
                             f"{list(self.__plot_data__)}")
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if self.__plot_is_disabled__():
            return
        titles = []  # pragma: no cover - needs matplotlib
        for title, data in self.__plot_data__.items():
            if len(data.step) > 0:
                titles.append(title)
                self.plt.plot(data.step, data.value)
        self.plt.legend(titles, loc="upper left")
        if path is None:
            self.plt.show()
        else:
            self.plt.savefig(path)
        self.plt.clf()

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
