"""paddle_tpu.utils.profiler — profiling.

TPU-native rebuild of reference python/paddle/fluid/profiler.py (+
platform/profiler.cc). The reference collects per-op CUDA timings; on TPU
the equivalent signal is an XLA trace viewable in TensorBoard/Perfetto,
captured via jax.profiler. A lightweight host-side timer table covers the
start/stop/print surface of the reference API.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

_records = defaultdict(lambda: [0.0, 0])
_trace_dir = None
_profiling_active = False  # the reference's core.is_profiler_enabled()


def start_profiler(state="All", tracer_option=None, trace_dir=None):
    """reference: profiler.start_profiler. Starts a jax.profiler trace."""
    global _trace_dir, _profiling_active
    _trace_dir = trace_dir or "/tmp/paddle_tpu_trace"
    jax.profiler.start_trace(_trace_dir)
    _profiling_active = True


def stop_profiler(sorted_key=None, profile_path=None):
    global _profiling_active
    _profiling_active = False
    jax.profiler.stop_trace()
    print(f"[paddle_tpu.profiler] XLA trace written to {_trace_dir} "
          "(open with TensorBoard / Perfetto)")
    if _records:
        print_stats()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None):
    """reference: fluid.profiler.profiler context manager."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def scope(name):
    """Host-side named timer + device annotation (StepTraceAnnotation)."""
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield
    dt = time.perf_counter() - t0
    _records[name][0] += dt
    _records[name][1] += 1


record_event = scope


def print_stats():
    print(f"{'name':<40}{'calls':>8}{'total_s':>12}{'avg_ms':>12}")
    for name, (total, calls) in sorted(_records.items(),
                                       key=lambda kv: -kv[1][0]):
        print(f"{name:<40}{calls:>8}{total:>12.4f}"
              f"{1000 * total / max(calls, 1):>12.4f}")


def reset_profiler():
    _records.clear()


def summarize_trace(trace_dir, top=20, steps=1):
    """Aggregate DEVICE op time from a jax.profiler trace directory
    (the Chrome-format .trace.json.gz jax writes) into per-op-family
    totals — "where does my step go?" without leaving the terminal.

    Returns a list of (family, total_ms / steps) sorted descending;
    also prints a table. `steps` divides totals by the number of steps
    captured inside the trace window. Host-side python frames, jit
    wrappers and transfer bookkeeping are excluded; op names are
    grouped by their XLA fusion family (e.g. every `multiply_reduce
    _fusion.N` variant aggregates into `multiply_reduce_fusion`).

    This is the tool the round-4 ResNet diagnosis used to find batch
    norm's reduce chains at ~70% of step time while convs ran at peak
    (docs/perf_r04.md)."""
    import collections
    import glob
    import gzip
    import json
    import os

    files = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not files:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {trace_dir!r} — pass the "
            "directory given to start_profiler()/jax.profiler.trace")
    if len(files) > 1:
        print(f"[summarize_trace] {len(files)} trace files found; "
              f"reading newest: {files[-1]}")
    skip = ("$", "jit_", "PjitFunction", "np.asarray", "trace",
            "ArrayImpl", "ParseArguments", "PythonRefManager",
            "PJRT_", "copy-start", "slice-start")
    tot = collections.Counter()
    with gzip.open(files[-1]) as fh:
        data = json.load(fh)
    events = data.get("traceEvents", [])
    # Identify the device lanes from the trace's process metadata (ph=M
    # process_name events whose name carries the device identity, e.g.
    # "/device:TPU:0 ..."), so host-side 'X' events can't inflate op
    # totals regardless of their names (r4 advisor finding).
    device_pids = {
        e.get("pid") for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and any(tag in str(e.get("args", {}).get("name", ""))
                for tag in ("/device:", "TPU", "GPU", "XLA"))
    }
    if not device_pids:
        print("[summarize_trace] no device lanes in process metadata; "
              "falling back to name-substring host filtering "
              "(approximate)")
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        n = e.get("name", "?")
        if any(s in n for s in skip) or n.isdigit():
            continue
        tot[n.split(".")[0]] += e["dur"]
    fams = [(name, d / 1e3 / max(steps, 1))
            for name, d in tot.most_common(top)]
    total = sum(d for _, d in fams)
    print(f"{'op family':<44}{'ms/step':>10}")
    for name, ms in fams:
        print(f"{name[:43]:<44}{ms:>10.2f}")
    print(f"{'TOTAL (top ' + str(top) + ')':<44}{total:>10.2f}")
    return fams


# --- paddle.utils.profiler parity (reference: utils/profiler.py) -----------

import sys as _sys


class ProfilerOptions:
    """reference utils/profiler.py:ProfilerOptions — option dict with
    'none' → None resolution."""

    def __init__(self, options=None):
        self.options = {
            "state": "All",
            "sorted_key": "default",
            "tracer_level": "Default",
            "batch_range": [0, _sys.maxsize],
            "output_thread_detail": False,
            "profile_path": "none",
            "timeline_path": "none",
            "op_summary_path": "none",
        }
        if options is not None:
            for key in self.options:
                if options.get(key, None) is not None:
                    self.options[key] = options[key]

    def with_state(self, state):
        self.options["state"] = state
        return self

    def __getitem__(self, name):
        if self.options.get(name, None) is None:
            raise ValueError(
                f"ProfilerOptions does not have an option named {name}.")
        v = self.options[name]
        return None if isinstance(v, str) and v == "none" else v


_current_profiler = None


class Profiler:
    """reference utils/profiler.py:Profiler — context-manager +
    batch-range driver over start/stop_profiler."""

    def __init__(self, enabled=True, options=None):
        self.profiler_options = options if options is not None \
            else ProfilerOptions()
        self.batch_id = 0
        self.enabled = enabled

    def __enter__(self):
        global _current_profiler
        self.previous_profiler = _current_profiler
        _current_profiler = self
        if self.enabled and self.profiler_options["batch_range"][0] == 0:
            self.start()
        return self

    def __exit__(self, exception_type, exception_value, traceback):
        global _current_profiler
        _current_profiler = self.previous_profiler
        if self.enabled:
            self.stop()

    def start(self):
        if not self.enabled:
            return
        import warnings
        try:
            start_profiler(
                state=self.profiler_options["state"],
                tracer_option=self.profiler_options["tracer_level"])
        except Exception as e:  # pragma: no cover
            warnings.warn("Profiler is not enabled because following "
                          f"exception:\n{e}")

    def stop(self):
        if not self.enabled or not _profiling_active:
            return
        import warnings
        try:
            stop_profiler(
                sorted_key=self.profiler_options["sorted_key"],
                profile_path=self.profiler_options["profile_path"])
        except Exception as e:  # pragma: no cover
            warnings.warn("Profiler is not disabled because following "
                          f"exception:\n{e}")

    def reset(self):
        if self.enabled and self.profiler_options["state"] != "Off":
            reset_profiler()

    def record_step(self, change_profiler_status=True):
        if not self.enabled:
            return
        self.batch_id += 1
        if not change_profiler_status:
            return
        lo, hi = self.profiler_options["batch_range"]
        if self.batch_id == lo:
            # reference gate: core.is_profiler_enabled() — reset a trace
            # that is already running, start one otherwise
            if _profiling_active:
                self.reset()
            else:
                self.start()
        if self.batch_id == hi:
            self.stop()


def get_profiler():
    """reference utils/profiler.py:get_profiler — the active Profiler,
    creating a disabled default if none is in scope."""
    global _current_profiler
    if _current_profiler is None:
        _current_profiler = Profiler(enabled=False)
    return _current_profiler
