"""paddle_tpu.utils.profiler — profiling.

TPU-native rebuild of reference python/paddle/fluid/profiler.py (+
platform/profiler.cc). The reference collects per-op CUDA timings; on TPU
the equivalent signal is an XLA trace viewable in TensorBoard/Perfetto,
captured via jax.profiler. A lightweight host-side timer table covers the
start/stop/print surface of the reference API.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

_records = defaultdict(lambda: [0.0, 0])
_trace_dir = None


def start_profiler(state="All", tracer_option=None, trace_dir=None):
    """reference: profiler.start_profiler. Starts a jax.profiler trace."""
    global _trace_dir
    _trace_dir = trace_dir or "/tmp/paddle_tpu_trace"
    jax.profiler.start_trace(_trace_dir)


def stop_profiler(sorted_key=None, profile_path=None):
    jax.profiler.stop_trace()
    print(f"[paddle_tpu.profiler] XLA trace written to {_trace_dir} "
          "(open with TensorBoard / Perfetto)")
    if _records:
        print_stats()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None):
    """reference: fluid.profiler.profiler context manager."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def scope(name):
    """Host-side named timer + device annotation (StepTraceAnnotation)."""
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield
    dt = time.perf_counter() - t0
    _records[name][0] += dt
    _records[name][1] += 1


record_event = scope


def print_stats():
    print(f"{'name':<40}{'calls':>8}{'total_s':>12}{'avg_ms':>12}")
    for name, (total, calls) in sorted(_records.items(),
                                       key=lambda kv: -kv[1][0]):
        print(f"{name:<40}{calls:>8}{total:>12.4f}"
              f"{1000 * total / max(calls, 1):>12.4f}")


def reset_profiler():
    _records.clear()
