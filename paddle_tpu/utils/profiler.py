"""paddle_tpu.utils.profiler — profiling.

TPU-native rebuild of reference python/paddle/fluid/profiler.py (+
platform/profiler.cc). The reference collects per-op CUDA timings; on TPU
the equivalent signal is an XLA trace viewable in TensorBoard/Perfetto,
captured via jax.profiler. A lightweight host-side timer table covers the
start/stop/print surface of the reference API.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

_records = defaultdict(lambda: [0.0, 0])
_trace_dir = None


def start_profiler(state="All", tracer_option=None, trace_dir=None):
    """reference: profiler.start_profiler. Starts a jax.profiler trace."""
    global _trace_dir
    _trace_dir = trace_dir or "/tmp/paddle_tpu_trace"
    jax.profiler.start_trace(_trace_dir)


def stop_profiler(sorted_key=None, profile_path=None):
    jax.profiler.stop_trace()
    print(f"[paddle_tpu.profiler] XLA trace written to {_trace_dir} "
          "(open with TensorBoard / Perfetto)")
    if _records:
        print_stats()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None):
    """reference: fluid.profiler.profiler context manager."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def scope(name):
    """Host-side named timer + device annotation (StepTraceAnnotation)."""
    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield
    dt = time.perf_counter() - t0
    _records[name][0] += dt
    _records[name][1] += 1


record_event = scope


def print_stats():
    print(f"{'name':<40}{'calls':>8}{'total_s':>12}{'avg_ms':>12}")
    for name, (total, calls) in sorted(_records.items(),
                                       key=lambda kv: -kv[1][0]):
        print(f"{name:<40}{calls:>8}{total:>12.4f}"
              f"{1000 * total / max(calls, 1):>12.4f}")


def reset_profiler():
    _records.clear()


def summarize_trace(trace_dir, top=20, steps=1):
    """Aggregate DEVICE op time from a jax.profiler trace directory
    (the Chrome-format .trace.json.gz jax writes) into per-op-family
    totals — "where does my step go?" without leaving the terminal.

    Returns a list of (family, total_ms / steps) sorted descending;
    also prints a table. `steps` divides totals by the number of steps
    captured inside the trace window. Host-side python frames, jit
    wrappers and transfer bookkeeping are excluded; op names are
    grouped by their XLA fusion family (e.g. every `multiply_reduce
    _fusion.N` variant aggregates into `multiply_reduce_fusion`).

    This is the tool the round-4 ResNet diagnosis used to find batch
    norm's reduce chains at ~70% of step time while convs ran at peak
    (docs/perf_r04.md)."""
    import collections
    import glob
    import gzip
    import json
    import os

    files = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not files:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {trace_dir!r} — pass the "
            "directory given to start_profiler()/jax.profiler.trace")
    if len(files) > 1:
        print(f"[summarize_trace] {len(files)} trace files found; "
              f"reading newest: {files[-1]}")
    skip = ("$", "jit_", "PjitFunction", "np.asarray", "trace",
            "ArrayImpl", "ParseArguments", "PythonRefManager",
            "PJRT_", "copy-start", "slice-start")
    tot = collections.Counter()
    with gzip.open(files[-1]) as fh:
        data = json.load(fh)
    events = data.get("traceEvents", [])
    # Identify the device lanes from the trace's process metadata (ph=M
    # process_name events whose name carries the device identity, e.g.
    # "/device:TPU:0 ..."), so host-side 'X' events can't inflate op
    # totals regardless of their names (r4 advisor finding).
    device_pids = {
        e.get("pid") for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and any(tag in str(e.get("args", {}).get("name", ""))
                for tag in ("/device:", "TPU", "GPU", "XLA"))
    }
    if not device_pids:
        print("[summarize_trace] no device lanes in process metadata; "
              "falling back to name-substring host filtering "
              "(approximate)")
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        n = e.get("name", "?")
        if any(s in n for s in skip) or n.isdigit():
            continue
        tot[n.split(".")[0]] += e["dur"]
    fams = [(name, d / 1e3 / max(steps, 1))
            for name, d in tot.most_common(top)]
    total = sum(d for _, d in fams)
    print(f"{'op family':<44}{'ms/step':>10}")
    for name, ms in fams:
        print(f"{name[:43]:<44}{ms:>10.2f}")
    print(f"{'TOTAL (top ' + str(top) + ')':<44}{total:>10.2f}")
    return fams
