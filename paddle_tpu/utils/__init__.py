"""paddle_tpu.utils — profiler, debug guards, logging (reference:
python/paddle/fluid/profiler.py, platform/profiler; log_helper.py)."""
from . import profiler
from . import debug
from . import log
from .debug import check_nan_inf, enable_nan_guard
from .log import get_logger, logger
from .plot import Ploter  # noqa: F401,E402
from .profiler import (ProfilerOptions, Profiler,  # noqa: F401,E402
                       get_profiler)
