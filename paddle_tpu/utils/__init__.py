"""paddle_tpu.utils — profiler, debug guards, logging (reference:
python/paddle/fluid/profiler.py, platform/profiler; debugger)."""
from . import profiler
from . import debug
from .debug import check_nan_inf, enable_nan_guard
