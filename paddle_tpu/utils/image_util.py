"""paddle.utils.image_util parity (reference:
python/paddle/utils/image_util.py) — thin numpy helpers over the
dataset/image.py toolkit the rebuild already ships."""
from __future__ import annotations

import numpy as np

from ..dataset import image as _img


def resize_image(img, target_size):
    """reference image_util.py:20 — resize so the SHORT side equals
    target_size (PIL image or HWC array in)."""
    arr = np.asarray(img)
    return _img.resize_short(arr, target_size)


def flip(im):
    """reference image_util.py:33 — horizontal flip of a CHW or HWC
    image."""
    im = np.asarray(im)
    if im.ndim == 3 and im.shape[0] in (1, 3):   # CHW
        return im[:, :, ::-1]
    return im[:, ::-1]


def crop_img(im, inner_size, color=True, test=True):
    """reference image_util.py:45 — center crop at test time, random crop
    (+ random flip) at train time."""
    im = np.asarray(im)
    if test:
        return _img.center_crop(im, inner_size, is_color=color)
    out = _img.random_crop(im, inner_size, is_color=color)
    if np.random.rand() < 0.5:
        out = _img.left_right_flip(out, is_color=color)
    return out


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """reference image_util.py:96."""
    im = crop_img(im, crop_size, color=color, test=not is_train)
    im = _img.to_chw(im).astype("float32")
    mean = np.asarray(img_mean, "float32")
    if mean.size == im.shape[0]:        # per-channel mean
        mean = mean.reshape(-1, 1, 1)
    else:                               # full mean image
        mean = mean.reshape(im.shape)
    return (im - mean).flatten()


def load_image(img_path, is_color=True):
    """reference image_util.py:133."""
    return _img.load_image(img_path, is_color=is_color)


def oversample(img, crop_dims):
    """reference image_util.py:144 — 4 corners + center, plus mirrors
    (10 crops), the classic eval-time oversampling."""
    img = np.asarray(img)
    h, w = img.shape[:2]
    ch, cw = (crop_dims, crop_dims) if np.isscalar(crop_dims) else crop_dims
    starts = [(0, 0), (0, w - cw), (h - ch, 0), (h - ch, w - cw),
              ((h - ch) // 2, (w - cw) // 2)]
    crops = [img[r:r + ch, c:c + cw] for r, c in starts]
    crops += [c[:, ::-1] for c in crops]
    return np.stack(crops)
